"""GCP TPU VM substrate: provisions real Cloud TPU pod slices.

Reference analog: Azure Batch pool allocation (batch.py:921 create_pool
-> service allocates VMs -> start task). Cloud TPU has no hosted task
scheduler, so this substrate provisions slices with ``gcloud compute
tpus tpu-vm`` and bootstraps our node agent on every worker — the agent
then pulls work from the state store exactly like the fake/localhost
substrates.

Allocation model (SURVEY.md section 7 hard parts):
  - one pool = ``num_slices`` queued-resource/TPU-VM creations, each an
    atomic slice of ``accelerator_type``;
  - node recovery = slice recreation (there is no per-worker reboot of
    a slice member that preserves ICI);
  - stockout/quota errors surface in the pool entity for
    _block_for_nodes_ready-style classification (batch.py:661 analog).

Requires the ``gcloud`` CLI and network access; constructing the
substrate without them raises, so the rest of the framework (and all
tests) never touch this path.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import time as _time
from typing import Optional

from batch_shipyard_tpu.config.settings import (
    CredentialsSettings, PoolSettings)
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.substrate import base
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Allocation-error taxonomy lives in substrate/gcloud_errors.py — a
# table-driven classifier tested against captured real gcloud payloads
# (the resize error classification of the reference, batch.py:661-672).
from batch_shipyard_tpu.substrate import gcloud_errors  # noqa: E402


class GcpTpuSubstrate(base.ComputeSubstrate):
    def __init__(self, store: StateStore,
                 credentials: CredentialsSettings,
                 bootstrap_bundle_key: Optional[str] = None) -> None:
        if shutil.which("gcloud") is None:
            raise RuntimeError(
                "gcloud CLI is required for the tpu_vm substrate; use "
                "substrate: fake or localhost without it")
        if credentials.gcp is None:
            raise ValueError(
                "credentials.gcp is required for the tpu_vm substrate")
        self.store = store
        self.credentials = credentials
        self.project = credentials.gcp.project
        self.zone = credentials.gcp.zone
        self.bootstrap_bundle_key = bootstrap_bundle_key

    # ------------------------------ gcloud -----------------------------

    def _gcloud(self, *args: str, parse_json: bool = False,
                zone: Optional[str] = None):
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}"]
        zone = zone or self.zone
        if zone:
            cmd.append(f"--zone={zone}")
        if parse_json:
            cmd.append("--format=json")
        rc, out, err = util.subprocess_capture(cmd)
        if rc != 0:
            raise RuntimeError(f"gcloud failed ({rc}): {err.strip()}")
        return json.loads(out) if parse_json else out

    @staticmethod
    def slice_name(pool_id: str, slice_index: int) -> str:
        return f"shipyard-{pool_id}-s{slice_index}"

    # ---------------------------- interface ----------------------------

    def allocate_pool(self, pool: PoolSettings) -> None:
        assert pool.tpu is not None, "tpu_vm substrate requires tpu block"
        for s in range(pool.tpu.num_slices):
            self._create_slice(pool, s)

    def _create_slice(self, pool: PoolSettings, slice_index: int) -> None:
        tpu = pool.tpu
        name = self.slice_name(pool.id, slice_index)
        args = ["create", name,
                f"--accelerator-type={tpu.accelerator_type}",
                f"--version={tpu.runtime_version}"]
        if tpu.provisioning_model == "spot":
            args.append("--spot")
        elif tpu.provisioning_model == "reserved":
            args.append(f"--reserved")
            if tpu.reservation_name:
                args.append(f"--reservation={tpu.reservation_name}")
        if tpu.network:
            args.append(f"--network={tpu.network}")
        if tpu.subnetwork:
            args.append(f"--subnetwork={tpu.subnetwork}")
        try:
            self._gcloud(*args, zone=pool.zone)
        except RuntimeError as exc:
            err = gcloud_errors.classify(str(exc))
            record = {
                "allocation_error": str(exc),
                "allocation_error_kind": err.kind,
                "allocation_error_fatal": err.fatal,
                "allocation_error_retry": err.retry}
            if err.retry == "other_zone":
                advisory = self._stockout_advisory(pool)
                if advisory:
                    record["allocation_error_advisory"] = advisory
            self.store.merge_entity(
                names.TABLE_POOLS, "pools", pool.id, record)
            raise
        self._register_workers(pool, slice_index)
        self._bootstrap_agents(pool, slice_index)

    def _stockout_advisory(self, pool: PoolSettings) -> Optional[str]:
        """On stockout, name sibling zones still offering the type
        (substrate/quota.py; advisory only — never raises).
        ``quota_client`` attribute injects a fake for tests."""
        try:
            from batch_shipyard_tpu.substrate import quota as quota_mod
            client = getattr(self, "quota_client", None)
            if client is None:
                client = quota_mod.TpuQuotaClient(self.project)
            failed_zone = pool.zone or self.zone or ""
            region = quota_mod._zone_region(failed_zone)
            candidates = [f"{region}-{s}" for s in "abcdef"]
            return quota_mod.stockout_advisory(
                client, pool.tpu.accelerator_type, failed_zone,
                candidates)
        except Exception:  # noqa: BLE001 - advisory only
            return None

    def _register_workers(self, pool: PoolSettings,
                          slice_index: int) -> None:
        name = self.slice_name(pool.id, slice_index)
        desc = self._gcloud("describe", name, parse_json=True,
                            zone=pool.zone)
        endpoints = desc.get("networkEndpoints", [])
        workers = pool.tpu.workers_per_slice
        for w, endpoint in enumerate(endpoints[:workers]):
            node_id = f"{pool.id}-s{slice_index}-w{w}"
            self.store.upsert_entity(
                names.TABLE_NODES, pool.id, node_id, {
                    "state": "creating",
                    "hostname": f"{name}-w{w}",
                    "internal_ip": endpoint.get("ipAddress", ""),
                    "external_ip": endpoint.get(
                        "accessConfig", {}).get("externalIp", ""),
                    "node_index": slice_index * workers + w,
                    "slice_index": slice_index, "worker_index": w,
                    "tpu_name": name, "zone": pool.zone or self.zone,
                    "registered_at": _time.time()})

    def _bootstrap_agents(self, pool: PoolSettings,
                          slice_index: int) -> None:
        """Install + systemd-launch the node agent on every worker via
        ``gcloud ... ssh --worker=all`` (the start-task analog,
        fleet.py:1317-1437)."""
        name = self.slice_name(pool.id, slice_index)
        storage = self.credentials.storage
        workers = pool.tpu.workers_per_slice
        script = _bootstrap_script(
            pool, storage_backend=storage.backend,
            storage_bucket=storage.bucket or "",
            storage_prefix=storage.prefix,
            slice_index=slice_index, workers=workers,
            bundle_key=self.bootstrap_bundle_key or "")
        self._gcloud("ssh", name, "--worker=all",
                     f"--command={script}", zone=pool.zone)

    def deallocate_pool(self, pool_id: str) -> None:
        rows = list(self.store.query_entities(
            names.TABLE_NODES, partition_key=pool_id))
        slices = sorted({(row.get("tpu_name"), row.get("zone"))
                         for row in rows if row.get("tpu_name")})
        for name, zone in slices:
            try:
                self._gcloud("delete", name, "--quiet", zone=zone)
            except RuntimeError:
                logger.exception("failed deleting %s", name)
        for row in rows:
            self.store.delete_entity(
                names.TABLE_NODES, pool_id, row["_rk"])

    def resize_pool(self, pool: PoolSettings, num_slices: int) -> None:
        current = sorted({
            int(row["slice_index"]) for row in self.store.query_entities(
                names.TABLE_NODES, partition_key=pool.id)})
        have = len(current)
        if num_slices > have:
            for s in range(have, num_slices):
                self._create_slice(pool, s)
        else:
            for s in current[num_slices:]:
                self._delete_slice(pool.id, s)

    def _delete_slice(self, pool_id: str, slice_index: int) -> None:
        name = self.slice_name(pool_id, slice_index)
        zone = None
        for row in self.store.query_entities(
                names.TABLE_NODES, partition_key=pool_id):
            if int(row.get("slice_index", -1)) == slice_index:
                zone = row.get("zone")
                break
        self._gcloud("delete", name, "--quiet", zone=zone)
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool_id)):
            if int(row.get("slice_index", -1)) == slice_index:
                self.store.delete_entity(
                    names.TABLE_NODES, pool_id, row["_rk"])

    def recreate_slice(self, pool: PoolSettings, slice_index: int) -> None:
        try:
            self._delete_slice(pool.id, slice_index)
        except RuntimeError:
            logger.warning("delete of slice %d failed; recreating anyway",
                           slice_index)
        self._create_slice(pool, slice_index)

    def deallocate_slice(self, pool: PoolSettings,
                         slice_index: int) -> None:
        self._delete_slice(pool.id, slice_index)

    def refresh_node_states(self, pool: PoolSettings) -> None:
        """Poll slice states and mark nodes of reclaimed slices
        'preempted' (gcloud_errors.is_preemption_state) — the
        $PreemptedNodeCount sample feeding autoscale
        rebalance_preemption_percentage and slice-recreate recovery.
        Called by the autoscale tick; cost is one describe per
        slice."""
        rows_by_slice: dict[int, list[dict]] = {}
        for row in self.store.query_entities(
                names.TABLE_NODES, partition_key=pool.id):
            rows_by_slice.setdefault(
                int(row.get("slice_index", -1)), []).append(row)
        for s in range(pool.tpu.num_slices if pool.tpu else 0):
            name = self.slice_name(pool.id, s)
            try:
                desc = self._gcloud("describe", name, parse_json=True,
                                    zone=pool.zone)
                state = desc.get("state")
            except RuntimeError as exc:
                if "not found" in str(exc).lower():
                    # Slice resource is gone: reclaimed.
                    state = "TERMINATED"
                else:
                    # Transient describe failure (network/API/auth) is
                    # NOT evidence of preemption — marking healthy
                    # nodes preempted would empty the pool's
                    # schedulable set on a blip.
                    logger.warning(
                        "describe of %s failed (%s); skipping "
                        "preemption check this tick", name, exc)
                    continue
            if not gcloud_errors.is_preemption_state(state):
                continue
            for row in rows_by_slice.get(s, []):
                if row.get("state") != "preempted":
                    logger.warning(
                        "slice %s is %s; marking node %s preempted",
                        name, state, row["_rk"])
                    self.store.merge_entity(
                        names.TABLE_NODES, pool.id, row["_rk"],
                        {"state": "preempted"})

    def suspend_pool(self, pool: PoolSettings) -> None:
        """gcloud tpu-vm stop on every slice (billing pause)."""
        for s in range(pool.tpu.num_slices):
            self._gcloud("stop", self.slice_name(pool.id, s),
                         zone=pool.zone)
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool.id)):
            self.store.merge_entity(names.TABLE_NODES, pool.id,
                                    row["_rk"], {"state": "suspended"})

    def start_pool(self, pool: PoolSettings) -> None:
        for s in range(pool.tpu.num_slices):
            self._gcloud("start", self.slice_name(pool.id, s),
                         zone=pool.zone)
            self._bootstrap_agents(pool, s)

    def get_remote_login(self, pool_id: str,
                         node_id: str) -> Optional[tuple[str, int]]:
        try:
            row = self.store.get_entity(names.TABLE_NODES, pool_id,
                                        node_id)
        except KeyError:
            return None
        ip = row.get("external_ip") or row.get("internal_ip")
        return (ip, 22) if ip else None


def _bootstrap_script(pool: PoolSettings, storage_backend: str,
                      storage_bucket: str, storage_prefix: str,
                      slice_index: int, workers: int,
                      bundle_key: str) -> str:
    """Shell one-liner run on each worker to start the node agent.

    The boot template travels base64-encoded (no quoting hazards); a
    tiny remote python fills in the per-worker identity from
    TPU_WORKER_ID and hostname.
    """
    import base64
    template = {
        "storage": {"backend": storage_backend,
                    "bucket": storage_bucket,
                    "prefix": storage_prefix},
        "pool_config": {"pool_specification": {
            "id": pool.id,
            "substrate": "tpu_vm",
            "tpu": {
                "accelerator_type": pool.tpu.accelerator_type,
                "num_slices": pool.tpu.num_slices,
            },
            "task_slots_per_node": pool.task_slots_per_node,
            # Agents poll the queue fan-out; the shard count MUST
            # match what producers read from the stored pool spec or
            # messages on shards > 0 are never consumed.
            "task_queue_shards": pool.task_queue_shards,
        }},
        "identity": {
            "pool_id": pool.id,
            "node_id": f"{pool.id}-s{slice_index}-wWORKER",
            "node_index": slice_index * workers,  # + worker id remotely
            "hostname": "", "internal_ip": "",
            "slice_index": slice_index, "worker_index": 0,
        },
        "work_dir": "/var/shipyard",
        "run_nodeprep": True,
        "output_upload_cap_bytes": (
            pool.output_upload_cap_mb * 1024 * 1024
            if pool.output_upload_cap_mb else None),
    }
    b64 = base64.b64encode(json.dumps(template).encode()).decode()
    fill_py = (
        'import json,os,socket;'
        't=json.load(open("/tmp/shipyard_boot_t.json"));'
        'w=int(os.environ.get("TPU_WORKER_ID","0"));'
        'i=t["identity"];'
        'i["node_id"]=i["node_id"].replace("WORKER",str(w));'
        'i["worker_index"]=w;i["node_index"]=i["node_index"]+w;'
        'i["hostname"]=socket.gethostname();'
        'i["internal_ip"]=socket.gethostbyname(socket.gethostname());'
        'json.dump(t,open("/tmp/shipyard_boot.json","w"))')
    lines = [
        "sudo mkdir -p /var/shipyard",
        "sudo chmod 777 /var/shipyard",
        f"echo {b64} | base64 -d > /tmp/shipyard_boot_t.json",
        f"python3 -c '{fill_py}'",
        # Fetch the framework bundle from the state bucket if provided.
        (f"gsutil cp gs://{storage_bucket}/{bundle_key} /tmp/bst.tar.gz "
         "&& sudo tar xzf /tmp/bst.tar.gz -C /opt" if bundle_key else
         "true"),
        "sudo sh -c 'nohup python3 -m batch_shipyard_tpu.agent "
        "/tmp/shipyard_boot.json >/var/shipyard/agent.log 2>&1 &'",
    ]
    return " && ".join(lines)

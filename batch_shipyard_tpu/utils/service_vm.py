"""Shared lifecycle verbs for service VMs (monitoring, federation
proxies, slurm control plane).

The reference gives each service resource its own ssh/suspend/start/
status verb family (monitor: shipyard.py:2416-2573 +
convoy/fleet.py:4721-4878; fed proxy: shipyard.py:2573+; slurm:
shipyard.py:2918+). Here all of them ride one helper set over
substrate/gce_vm.GceVmManager and the service's registration row, so
every family behaves identically: suspend = instance stop (state
preserved, billing stops), start = instance start + state refresh,
status = live instance status next to the stored record, ssh = the
argv to reach the VM (callers exec it; tests assert on it)."""

from __future__ import annotations

from typing import Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def default_vms(project: Optional[str], zone: Optional[str] = None,
                vms=None, network: Optional[str] = None):
    """The shared ``vms``-injection fallback: tests pass a fake
    manager, production constructs a GceVmManager lazily (the import
    stays local so gcloud-less environments never pay for it)."""
    if vms is not None:
        return vms
    from batch_shipyard_tpu.substrate.gce_vm import GceVmManager
    return GceVmManager(project, zone=zone, network=network)


def ssh_argv(ip: str, username: Optional[str] = None,
             ssh_private_key: Optional[str] = None,
             command: Optional[str] = None) -> list[str]:
    """ssh argv for a service VM (reference _monitor_ssh analog:
    convoy/fleet.py:4721). Strict host checking is off because
    service VMs are recreated freely and their host keys churn."""
    argv = ["ssh", "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null"]
    if ssh_private_key:
        argv += ["-i", ssh_private_key]
    argv.append(f"{username}@{ip}" if username else ip)
    if command:
        argv.append(command)
    return argv


def suspend_vm(vms, name: str, store=None, table: str = "",
               pk: str = "", rk: str = "") -> None:
    """Stop a service VM in place (reference suspend_monitoring_
    resource analog, convoy/fleet.py:4735)."""
    vms.stop_vm(name)
    if store is not None and table:
        try:
            store.merge_entity(table, pk, rk or name,
                               {"state": "suspended"})
        except Exception:  # noqa: BLE001 - registration row optional
            logger.warning("no registration row to mark suspended "
                           "for %s", name)


def start_vm(vms, name: str, store=None, table: str = "",
             pk: str = "", rk: str = "") -> None:
    """Restart a suspended service VM."""
    vms.start_vm(name)
    if store is not None and table:
        try:
            store.merge_entity(table, pk, rk or name,
                               {"state": "running"})
        except Exception:  # noqa: BLE001
            logger.warning("no registration row to mark running "
                           "for %s", name)


def vm_status(vms, name: str, record: Optional[dict] = None) -> dict:
    """Stored record + live instance status (unknown when the probe
    fails — status must degrade, not raise, for a deleted VM)."""
    out = {"name": name, "record": record or {}}
    try:
        out["vm_status"] = vms.vm_status(name)
    except Exception as exc:  # noqa: BLE001 - live probe optional
        out["vm_status"] = f"unknown ({exc})"
    return out

"""Federation: constraint-based meta-scheduling across heterogeneous
pools (TPU pods of different shapes + CPU/GPU VM pools).

Reference analog: federation/federation.py (3237 LoC) — a daemon VM
holding a global-lock blob lease (:962), polling per-federation action
queues (:3135), filtering candidate pools with hard constraints (:1709:
pool state, vm size, location, registries, max active task backlog),
then greedy best-fit matching (:2084) with blacklisting/retry (:2786)
and poison-message zapping (fleet.py:5209).

TPU-native redesign, same architecture:
  - federations + member pools in TABLE_FEDERATIONS;
  - job actions as JSON blobs + queue messages on the federation
    queue (storage.py:1276 analog);
  - the daemon is HA via a state-store lease; constraints understand
    TPU shapes (accelerator generation, minimum chips/slices) instead
    of Azure vm sizes;
  - scheduling = hard-constraint filter -> greedy best fit by idle
    slot count -> submit through the ordinary jobs manager onto the
    chosen pool.

Job-level constraints (jobs.yaml federation_constraints block):
  pool_ids: [..]            explicit allowlist
  accelerator_generation:   e.g. 'v5litepod' / 'v6e'
  min_chips: int            total chips in the pool's slices
  min_idle_nodes: int
  max_active_task_backlog:  float ratio of queued tasks to slots
  substrate: tpu_vm|fake|localhost
  location: str             pool zone must match (PoolConstraints
                            .location, reference federation.py:190)
  registries: [server, ..]  pool must hold registry logins for every
                            listed server (has_registry_login check,
                            reference federation.py:1927)
  low_priority_nodes:       {allow: bool, exclusive: bool} — dedicated
                            -only or preemptible-only execution
                            (reference federation.py:1947-1975)
  autoscale: {allow: bool}  zero-capacity pools qualify if they can
                            autoscale (reference federation.py:1952)
  compute_node:             node-level filter (:1939 analog):
    exclusive: bool           node must be running nothing
    min_task_slots: int       node slot capacity floor
    min_free_slots: int       current free-slot floor
    min_chips_per_worker: int TPU chips attached per worker
  required_target:          {pool_id: str, node_id: str|null} — pin
                            the job to THIS pool (and node),
                            bypassing best-fit (:2030 analog)
"""

from __future__ import annotations

import datetime as _dt
import json
import threading
import time
import uuid
from typing import Optional

from batch_shipyard_tpu.agent import cascade
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import leases as state_leases
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def _iso_epoch(value):
    from batch_shipyard_tpu.goodput import events as gp_events
    return gp_events.iso_to_epoch(value)

GLOBAL_LOCK_KEY = "federation/global-lock"
LOCK_SECONDS = 30.0


# ----------------------------- client side -----------------------------

def create_federation(store: StateStore, federation_id: str,
                      force: bool = False) -> None:
    entity = {"created_at": util.datetime_utcnow_iso(), "pools": []}
    if force:
        store.upsert_entity(names.TABLE_FEDERATIONS, "fed",
                            federation_id, entity)
    else:
        try:
            store.insert_entity(names.TABLE_FEDERATIONS, "fed",
                                federation_id, entity)
        except EntityExistsError:
            raise ValueError(f"federation {federation_id} exists")


def destroy_federation(store: StateStore, federation_id: str) -> None:
    # Drop every job-location + zap row with the federation (the
    # reference GCs its job tables on destroy, convoy/storage.py:898).
    for row in list(store.query_entities(names.TABLE_FEDJOBS,
                                         partition_key=federation_id)):
        try:
            store.delete_entity(names.TABLE_FEDJOBS, federation_id,
                                row["_rk"])
        except NotFoundError:
            pass
    try:
        store.delete_entity(names.TABLE_FEDERATIONS, "fed",
                            federation_id)
    except NotFoundError:
        pass


GC_GRACE_SECONDS = 300.0


def gc_federation_jobs(store: StateStore, federation_id: str,
                       grace_seconds: float = GC_GRACE_SECONDS,
                       ) -> list[str]:
    """Remove stale job-location rows — placements whose job no
    longer exists on the recorded pool (deleted behind the
    federation's back, or the pool itself is gone). Reference analog:
    gc_federation_jobs, convoy/storage.py:898. Returns the removed
    job ids.

    Rows younger than ``grace_seconds`` are never collected: the
    scheduler inserts the placement row BEFORE creating the job on
    the pool, so a GC racing that window would delete a live
    placement and let a later action re-place the job elsewhere.
    """
    removed = []
    horizon = util.utcnow().timestamp() - grace_seconds
    for row in list(store.query_entities(names.TABLE_FEDJOBS,
                                         partition_key=federation_id)):
        job_id = row["_rk"]
        if job_id.startswith("zap$"):
            continue
        born = row.get("merged_at") or row.get("scheduled_at")
        if born:
            try:
                ts = _dt.datetime.strptime(
                    born, "%Y-%m-%dT%H:%M:%S.%fZ").replace(
                        tzinfo=_dt.timezone.utc).timestamp()
                if ts > horizon:
                    continue
            except ValueError:
                pass
        pool_id = row.get("pool_id")
        stale = False
        if not pool_id or not pool_mgr.pool_exists(store, pool_id):
            stale = True
        else:
            try:
                jobs_mgr.get_job(store, pool_id, job_id)
            except jobs_mgr.JobNotFoundError:
                stale = True
        if stale:
            try:
                store.delete_entity(names.TABLE_FEDJOBS, federation_id,
                                    job_id)
                removed.append(job_id)
            except NotFoundError:
                pass
    if removed:
        logger.info("federation %s: GC removed stale job rows %s",
                    federation_id, removed)
    return removed


def get_federation(store: StateStore, federation_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_FEDERATIONS, "fed",
                                federation_id)
    except NotFoundError:
        raise ValueError(f"federation {federation_id} does not exist")


def list_federations(store: StateStore) -> list[dict]:
    return list(store.query_entities(names.TABLE_FEDERATIONS,
                                     partition_key="fed"))


def add_pool_to_federation(store: StateStore, federation_id: str,
                           pool_id: str) -> None:
    fed = get_federation(store, federation_id)
    pools = set(fed.get("pools", []))
    pools.add(pool_id)
    store.merge_entity(names.TABLE_FEDERATIONS, "fed", federation_id,
                       {"pools": sorted(pools)},
                       if_match=fed["_etag"])


def remove_pool_from_federation(store: StateStore, federation_id: str,
                                pool_id: str) -> None:
    fed = get_federation(store, federation_id)
    pools = set(fed.get("pools", []))
    pools.discard(pool_id)
    store.merge_entity(names.TABLE_FEDERATIONS, "fed", federation_id,
                       {"pools": sorted(pools)},
                       if_match=fed["_etag"])


def submit_job_to_federation(store: StateStore, federation_id: str,
                             jobs_config: dict) -> str:
    """fed jobs add: serialize the job spec as a blob + queue message
    (batch.py:5900 generate_info_metadata + storage.py:1959 analog)."""
    get_federation(store, federation_id)
    action_id = uuid.uuid4().hex[:12]
    job_ids = [j["id"] for j in
               jobs_config.get("job_specifications", [])]
    blob_key = names.federation_job_blob_key(
        federation_id, "-".join(job_ids) or "job", action_id)
    store.put_object(blob_key, json.dumps(jobs_config).encode())
    store.put_message(names.federation_queue(federation_id),
                      json.dumps({
                          "action": "add_job", "action_id": action_id,
                          "blob_key": blob_key,
                      }).encode())
    return action_id


def zap_action(store: StateStore, federation_id: str,
               action_id: str) -> None:
    """fed jobs zap: mark a poison action so the daemon drops it
    (fleet.py:5209 analog)."""
    store.upsert_entity(names.TABLE_FEDJOBS, federation_id,
                        f"zap${action_id}", {"zapped": True})


def locate_federation_job(store: StateStore, federation_id: str,
                          job_id: str) -> str:
    """Which pool did the scheduler place this job on? (job locator
    table analog, storage.py:1276)."""
    try:
        row = store.get_entity(names.TABLE_FEDJOBS, federation_id,
                               job_id)
    except NotFoundError:
        raise ValueError(
            f"job {job_id} is not scheduled in federation "
            f"{federation_id}")
    return row["pool_id"]


def terminate_federation_job(store: StateStore, federation_id: str,
                             job_id: str) -> str:
    """fed jobs term: route the terminate to the pool the job landed
    on. Returns that pool id."""
    pool_id = locate_federation_job(store, federation_id, job_id)
    jobs_mgr.terminate_job(store, pool_id, job_id)
    return pool_id


def delete_federation_job(store: StateStore, federation_id: str,
                          job_id: str) -> str:
    """fed jobs del: route the delete and drop the locator row."""
    pool_id = locate_federation_job(store, federation_id, job_id)
    jobs_mgr.delete_job(store, pool_id, job_id)
    store.delete_entity(names.TABLE_FEDJOBS, federation_id, job_id)
    return pool_id


def list_federation_jobs(store: StateStore,
                         federation_id: str) -> list[dict]:
    return [row for row in store.query_entities(
        names.TABLE_FEDJOBS, partition_key=federation_id)
        if not row["_rk"].startswith("zap$")]


# --------------------------- constraint match --------------------------

def _pool_facts(store: StateStore, pool_id: str,
                stale_seconds: float = 30.0) -> Optional[dict]:
    """Assemble the scheduling facts for one member pool, including
    per-node occupancy (the node-level facts behind the reference's
    _filter_pool_nodes_with_constraints, federation.py:1939) and
    per-node LIVENESS (heartbeat/registration freshness — the
    elastic evaluator's capacity signal: a crashed node's row lingers
    in a non-offline state, and counting it as capacity would hide
    exactly the starvation cross-pool migration exists to fix)."""
    try:
        entity = pool_mgr.get_pool(store, pool_id)
    except pool_mgr.PoolNotFoundError:
        return None
    spec_raw = entity.get("spec") or {}
    try:
        pool = settings_mod.pool_settings(spec_raw)
    except (ValueError, KeyError):
        return None
    nodes = []
    now = time.time()
    for row in store.query_entities(names.TABLE_NODES,
                                    partition_key=pool_id):
        slots = int(row.get("task_slots",
                            pool.task_slots_per_node) or 1)
        running = int(row.get("running_tasks", 0) or 0)
        last_seen = float(row.get("heartbeat_at", 0) or 0)
        if last_seen <= 0:
            last_seen = float(row.get("registered_at", 0) or 0)
        fresh = (row.get("state") not in ("offline",)
                 and last_seen > 0
                 and now - last_seen <= stale_seconds)
        nodes.append({
            "node_id": row["_rk"],
            "state": row.get("state", "unknown"),
            "task_slots": slots,
            "running_tasks": running,
            "free_slots": max(0, slots - running),
            "fresh": fresh,
        })
    idle = [n for n in nodes if n["state"] == "idle"]
    ready = [n for n in nodes if n["state"] in pool_mgr.READY_STATES]
    backlog = sum(
        store.queue_length(q)
        for q in names.task_queues(pool_id, pool.task_queue_shards))
    slots = max(1, len(ready) * pool.task_slots_per_node)
    registries = {row.get("server")
                  for row in cascade.registry_manifest(store, pool_id)}
    return {
        "pool_id": pool_id,
        "pool": pool,
        "state": entity.get("state"),
        "zone": pool.zone,
        "registries": registries,
        "nodes": nodes,
        "nodes_total": len(nodes),
        "nodes_idle": len(idle),
        "nodes_ready": len(ready),
        "nodes_live": sum(1 for n in nodes if n["fresh"]),
        "free_slots": sum(n["free_slots"] for n in ready),
        "backlog": backlog,
        "backlog_ratio": backlog / slots,
        "chips": (pool.tpu.info.num_chips * pool.tpu.num_slices
                  if pool.tpu else 0),
        "autoscale_enabled": pool.autoscale.enabled,
    }


def filter_pools_hard_constraints(
        facts: list[dict], constraints: dict) -> list[dict]:
    """Hard-constraint pool filter (:1709 analog)."""
    out = []
    allow = constraints.get("pool_ids")
    for fact in facts:
        pool = fact["pool"]
        if fact["state"] not in ("ready",):
            continue
        if allow and fact["pool_id"] not in allow:
            continue
        if constraints.get("substrate") and (
                pool.substrate != constraints["substrate"]):
            continue
        gen = constraints.get("accelerator_generation")
        if gen:
            if pool.tpu is None:
                continue
            if not pool.tpu.accelerator_type.startswith(gen) and \
                    pool.tpu.info.generation.name != gen:
                continue
        if constraints.get("min_chips") and (
                fact["chips"] < constraints["min_chips"]):
            continue
        if constraints.get("min_idle_nodes") and (
                fact["nodes_idle"] < constraints["min_idle_nodes"]):
            continue
        max_backlog = constraints.get("max_active_task_backlog")
        if max_backlog is not None and (
                fact["backlog_ratio"] > float(max_backlog)):
            continue
        # location hard constraint (PoolConstraints.location, :190):
        # matches the pool's GCP zone.
        loc = constraints.get("location")
        if loc and fact.get("zone") != loc:
            continue
        # registry hard constraint (:1927 has_registry_login): the
        # pool must hold a credential row for every required server.
        regs = constraints.get("registries")
        if regs and not set(regs) <= (fact.get("registries") or set()):
            continue
        # dedicated-only / preemptible-only execution (:1947-1975).
        # On TPU pools preemptibility is pool-wide (provisioning
        # model); on VM pools it is the low-priority node count.
        lp = constraints.get("low_priority_nodes") or {}
        if lp.get("allow") is False and _pool_is_preemptible(pool):
            continue
        if lp.get("exclusive") and not _pool_is_preemptible(pool):
            continue
        out.append(fact)
    return out


def _pool_is_preemptible(pool) -> bool:
    if pool.tpu is not None:
        return pool.tpu.provisioning_model == "spot"
    return (pool.vm_count_low_priority > 0 and
            pool.vm_count_dedicated == 0)


def qualifying_nodes(fact: dict, constraints: dict) -> list[dict]:
    """Node-level filter (:1939 analog): which of the pool's nodes
    could run this job's tasks right now, under the compute_node
    constraints."""
    cn = constraints.get("compute_node") or {}
    pool = fact["pool"]
    out = []
    for node in fact.get("nodes", []):
        if node["state"] not in pool_mgr.READY_STATES:
            continue
        if cn.get("exclusive") and node["running_tasks"] > 0:
            continue
        if cn.get("min_task_slots") and (
                node["task_slots"] < int(cn["min_task_slots"])):
            continue
        min_free = int(cn.get("min_free_slots", 1) or 0)
        if node["free_slots"] < min_free:
            continue
        mcw = cn.get("min_chips_per_worker")
        if mcw:
            chips = pool.tpu.chips_per_worker if pool.tpu else 0
            if chips < int(mcw):
                continue
        out.append(node)
    return out


def filter_pool_nodes(facts: list[dict], constraints: dict,
                      required_nodes: int = 1) -> list[dict]:
    """Second-pass filter after the pool-level pass: keep pools with
    at least ``required_nodes`` qualifying nodes (the gang size —
    target-required capacity selection, :2030), or pools that could
    reach that capacity via autoscale when the constraints allow it
    (:1952). Annotates each fact with its qualifying node list."""
    autoscale_allow = (constraints.get("autoscale") or {}).get(
        "allow", True)
    out = []
    for fact in facts:
        nodes = qualifying_nodes(fact, constraints)
        fact = dict(fact, qualifying_nodes=nodes)
        if len(nodes) >= required_nodes:
            out.append(fact)
        elif (autoscale_allow and fact.get("autoscale_enabled") and
                fact["nodes_total"] < _autoscale_max_nodes(fact["pool"])):
            # Capacity could appear: bin as available-via-autoscale.
            fact["via_autoscale"] = True
            out.append(fact)
    return out


def _autoscale_max_nodes(pool) -> float:
    """Upper node bound the pool's autoscale can reach. A user
    formula has no statically-known ceiling — treat it as unbounded
    (the reference bins any steady autoscale-enabled pool as
    available, federation.py:1952)."""
    scenario = pool.autoscale.scenario
    if scenario is None:
        return float("inf")
    return (scenario.maximum_vm_count_dedicated +
            scenario.maximum_vm_count_low_priority)


def _job_required_nodes(job) -> int:
    """Gang size of the job's largest multi-instance task — the
    capacity the chosen pool must offer (target-required selection,
    reference federation.py:2030). Symbolic counts that resolve to
    'the whole pool' (pool_current_dedicated, ...) count as 1: every
    pool satisfies its own size by definition."""
    req = 1
    for raw in job.tasks:
        mi = raw.get("multi_instance") or {}
        n = mi.get("num_instances")
        if isinstance(n, int):
            req = max(req, n)
    return req


def greedy_best_fit(facts: list[dict]) -> Optional[dict]:
    """Greedy best-fit pool choice (:2084 analog): pools that satisfy
    the capacity NOW beat autoscale-pending ones; then most
    qualifying nodes, most free slots, lowest backlog ratio, largest
    pool."""
    if not facts:
        return None

    def key(f):
        qualifying = (len(f["qualifying_nodes"])
                      if "qualifying_nodes" in f else f["nodes_idle"])
        return (f.get("via_autoscale", False), -qualifying,
                -f.get("free_slots", 0), f["backlog_ratio"],
                -f["nodes_total"])

    return sorted(facts, key=key)[0]


# ----------------------------- daemon side -----------------------------

class FederationProcessor:
    """The HA scheduler daemon (FederationProcessor :2727 analog)."""

    def __init__(self, store: StateStore, owner: Optional[str] = None,
                 poll_interval: float = 1.0,
                 action_retry_delay: float = 5.0,
                 gc_interval: float = 300.0,
                 after_success_blackout: float = 0.0,
                 elastic_interval: float = 30.0,
                 elastic_grace_seconds: float = 60.0,
                 node_stale_seconds: float = 30.0) -> None:
        self.store = store
        self.owner = owner or f"fedproc-{uuid.uuid4().hex[:8]}"
        self.poll_interval = poll_interval
        self.action_retry_delay = action_retry_delay
        self.gc_interval = gc_interval
        # Cross-pool elasticity: every elastic_interval the lock
        # holder re-examines PLACED jobs — a gang starved below its
        # min_instances floor (or stranded on a pool with no live
        # capacity) for elastic_grace_seconds is atomically
        # re-targeted onto a sibling pool that satisfies its
        # constraints. <=0 disables the evaluator.
        self.elastic_interval = elastic_interval
        self.elastic_grace_seconds = elastic_grace_seconds
        self.node_stale_seconds = node_stale_seconds
        self._last_elastic = 0.0
        # proxy_options.scheduling.after_success_blackout_interval: a
        # pool that just received a job is deprioritized for this many
        # seconds, spreading rapid-fire placements across members
        # (reference federation.py blackout semantics). Soft: when
        # every eligible pool is blacked out, placement proceeds —
        # capacity beats spreading.
        self.after_success_blackout = after_success_blackout
        self._blackout_until: dict[str, float] = {}
        self.stop_event = threading.Event()
        self._lease = None
        self._last_gc = 0.0
        # Cross-pool migration is NOT idempotent across two
        # evaluators (two replicas re-targeting one job double-fans
        # the gang): the elastic pass holds its own named lease per
        # federation — fenced by the term epoch stamped into every
        # locator claim — on top of the coarse global lock, which
        # only serializes a single processor generation and has no
        # fencing for in-flight writes of a deposed holder.
        self._elastic_leases: dict[str, "state_leases.LeaderLease"] \
            = {}

    # -- lock ----------------------------------------------------------

    def _hold_global_lock(self) -> bool:
        if self._lease is not None:
            try:
                self._lease = self.store.renew_lease(self._lease,
                                                     LOCK_SECONDS)
                return True
            except Exception:
                self._lease = None
        self._lease = self.store.acquire_lease(
            GLOBAL_LOCK_KEY, LOCK_SECONDS, self.owner)
        return self._lease is not None

    # -- processing ----------------------------------------------------

    def process_once(self) -> int:
        """One poll cycle over all federations; returns actions
        processed. Only the lock holder schedules (HA :962)."""
        if not self._hold_global_lock():
            return 0
        processed = 0
        feds = list_federations(self.store)
        for fed in feds:
            processed += self._process_federation_queue(fed["_rk"], fed)
        now = time.monotonic()
        if now - self._last_gc >= self.gc_interval:
            self._last_gc = now
            for fed in feds:
                try:
                    gc_federation_jobs(self.store, fed["_rk"])
                except Exception:
                    logger.exception("federation GC failed for %s",
                                     fed["_rk"])
        if self.elastic_interval > 0 and \
                now - self._last_elastic >= self.elastic_interval:
            self._last_elastic = now
            for fed in feds:
                try:
                    processed += self.evaluate_elastic(fed["_rk"],
                                                       fed)
                except Exception:
                    logger.exception(
                        "federation elastic evaluation failed for "
                        "%s", fed["_rk"])
        return processed

    def _is_zapped(self, federation_id: str, action_id: str) -> bool:
        try:
            self.store.get_entity(names.TABLE_FEDJOBS, federation_id,
                                  f"zap${action_id}")
            return True
        except NotFoundError:
            return False

    def _process_federation_queue(self, federation_id: str,
                                  fed: dict) -> int:
        queue = names.federation_queue(federation_id)
        processed = 0
        for msg in self.store.get_messages(
                queue, max_messages=8, visibility_timeout=60.0):
            action = json.loads(msg.payload)
            action_id = action.get("action_id", "?")
            if self._is_zapped(federation_id, action_id):
                logger.warning("dropping zapped action %s", action_id)
                self.store.delete_message(msg)
                continue
            if action.get("action") == "add_job":
                done = self._schedule_add_job(federation_id, fed,
                                              action)
                if done:
                    self.store.delete_message(msg)
                    processed += 1
                else:
                    # No eligible pool now: back off and retry
                    # (blocked-action requeue, storage.py:1331).
                    self.store.update_message(
                        msg,
                        visibility_timeout=self.action_retry_delay)
            else:
                logger.error("unknown federation action %r", action)
                self.store.delete_message(msg)
        return processed

    def _schedule_add_job(self, federation_id: str, fed: dict,
                          action: dict) -> bool:
        try:
            jobs_config = json.loads(
                self.store.get_object(action["blob_key"]))
        except NotFoundError:
            logger.error("federation action blob missing: %s",
                         action.get("blob_key"))
            return True  # unrecoverable; drop
        jobs = settings_mod.job_settings_list(jobs_config)
        facts = [f for f in (
            _pool_facts(self.store, pid) for pid in fed.get("pools", []))
            if f is not None]
        all_ok = True
        for job in jobs:
            if not self._schedule_one_job(federation_id, fed, action,
                                          job, facts):
                all_ok = False
        return all_ok

    def _schedule_one_job(self, federation_id: str, fed: dict,
                          action: dict, job, facts: list[dict]) -> bool:
        action_id = action.get("action_id")
        constraints = dict(job.federation_constraints)
        target = constraints.get("required_target") or {}
        # A previously-placed job stays on its pool: a NEW action for
        # the same job id appends its tasks there with task-id
        # collision fixup; a RETRY of an already-applied action is a
        # no-op (the action_ids list is the reference's UniqueIds
        # dedup, federation.py:2567-2590).
        try:
            placed = self.store.get_entity(
                names.TABLE_FEDJOBS, federation_id, job.id)
        except NotFoundError:
            placed = None
        if placed is not None:
            if action_id in (placed.get("action_ids") or ()):
                logger.info(
                    "federation %s: action %s already applied to job "
                    "%s on pool %s", federation_id, action_id, job.id,
                    placed.get("pool_id"))
                return True
            return self._merge_into_placed_job(
                federation_id, job, placed, action_id,
                self._effective_node_pin(federation_id, job,
                                         target.get("node_id")))
        required_node = None
        if target.get("pool_id"):
            # Required-target select (:2030 analog): pin to THIS pool
            # (and node), bypassing constraint filtering + best-fit.
            choice = self._select_required_target(
                federation_id, fed, job, facts, target)
            if choice is None:
                return False
            required_node = self._effective_node_pin(
                federation_id, job, target.get("node_id"))
        else:
            eligible = filter_pools_hard_constraints(facts, constraints)
            eligible = filter_pool_nodes(
                eligible, constraints,
                required_nodes=_job_required_nodes(job))
            eligible = self._apply_blackout(eligible)
            choice = greedy_best_fit(eligible)
        if choice is None:
            logger.info(
                "federation %s: no eligible pool for job %s "
                "(constraints=%s)", federation_id, job.id, constraints)
            return False
        pool = choice["pool"]
        try:
            self.store.insert_entity(
                names.TABLE_FEDJOBS, federation_id, job.id, {
                    "pool_id": pool.id,
                    "action_id": action_id,
                    "action_ids": [action_id],
                    # Persisted so the elastic evaluator can re-apply
                    # the job's constraints when it later picks a
                    # MIGRATION target (the action blob is not
                    # consulted again after placement).
                    "constraints": constraints,
                    "scheduled_at": util.datetime_utcnow_iso(),
                })
        except EntityExistsError:
            return True  # lost a race with another scheduler pass
        try:
            jobs_mgr.add_jobs(self.store, pool, [job],
                              pool_id_override=pool.id,
                              required_node=required_node)
        except jobs_mgr.JobExistsError:
            pass  # already scheduled by a previous attempt
        self._note_placement(pool.id)
        logger.info("federation %s: job %s -> pool %s",
                    federation_id, job.id, pool.id)
        return True

    def _apply_blackout(self, eligible: list[dict]) -> list[dict]:
        if self.after_success_blackout <= 0 or not eligible:
            return eligible
        now = time.monotonic()
        open_pools = [f for f in eligible
                      if self._blackout_until.get(
                          f["pool_id"], 0.0) <= now]
        return open_pools or eligible

    def _note_placement(self, pool_id: str) -> None:
        if self.after_success_blackout > 0:
            self._blackout_until[pool_id] = (
                time.monotonic() + self.after_success_blackout)

    def _effective_node_pin(self, federation_id: str, job,
                            node_id: Optional[str]) -> Optional[str]:
        """A gang task pinned to ONE node could never rendezvous its
        other instances — honor the pool pin only (applies to first
        placement AND repeat-action merges)."""
        if node_id and any(
                (raw.get("multi_instance") or {}).get(
                    "num_instances") not in (None, 1)
                for raw in job.tasks):
            logger.warning(
                "federation %s: job %s has multi-instance tasks; "
                "ignoring required_target.node_id=%s (pool pin kept)",
                federation_id, job.id, node_id)
            return None
        return node_id

    def _select_required_target(self, federation_id: str, fed: dict,
                                job, facts: list[dict],
                                target: dict) -> Optional[dict]:
        pool_id = target["pool_id"]
        if pool_id not in fed.get("pools", []):
            logger.error(
                "federation %s: job %s requires pool %s which is not "
                "a member; dropping", federation_id, job.id, pool_id)
            return None
        fact = next((f for f in facts if f["pool_id"] == pool_id),
                    None)
        if fact is None or fact["state"] != "ready":
            return None  # requeue until the pool is up
        node_id = target.get("node_id")
        if node_id and not any(
                n["node_id"] == node_id and
                n["state"] in pool_mgr.READY_STATES
                for n in fact.get("nodes", [])):
            return None  # requeue until the pinned node is schedulable
        return fact

    def _merge_into_placed_job(self, federation_id: str, job,
                               placed: dict, action_id: str,
                               required_node: Optional[str]) -> bool:
        pool_id = placed["pool_id"]
        try:
            pool_entity = pool_mgr.get_pool(self.store, pool_id)
            pool = settings_mod.pool_settings(
                pool_entity.get("spec") or {})
        except (pool_mgr.PoolNotFoundError, ValueError, KeyError):
            logger.error(
                "federation %s: job %s placed on missing pool %s; "
                "dropping merge", federation_id, job.id, pool_id)
            return True
        if required_node is not None:
            # Same validation first placement gets: a pin to a node
            # that doesn't exist (typo, since-removed) would submit
            # tasks no agent will ever claim — they'd bounce forever.
            fact = _pool_facts(self.store, pool_id)
            if fact is None or not any(
                    n["node_id"] == required_node and
                    n["state"] in pool_mgr.READY_STATES
                    for n in fact.get("nodes", [])):
                logger.info(
                    "federation %s: merge for job %s requires node %s "
                    "which is not schedulable on pool %s; retrying",
                    federation_id, job.id, required_node, pool_id)
                return False  # requeue with backoff
        try:
            added = jobs_mgr.merge_tasks_into_job(
                self.store, pool, job, pool_id,
                required_node=required_node)
        except jobs_mgr.JobNotFoundError:
            # Job was deleted on the pool after placement: treat the
            # placement row as stale and re-place on the next pass.
            self.store.delete_entity(names.TABLE_FEDJOBS,
                                     federation_id, job.id)
            return False
        except jobs_mgr.JobExistsError as exc:
            logger.error("federation %s: merge into job %s failed: %s",
                         federation_id, job.id, exc)
            return True  # non-retryable id conflict; drop
        # Full ledger, never trimmed: dropping old ids would let a
        # late redelivery of an ancient action re-merge its tasks.
        action_ids = list(placed.get("action_ids") or [])
        action_ids.append(action_id)
        self.store.merge_entity(
            names.TABLE_FEDJOBS, federation_id, job.id,
            {"action_ids": action_ids,
             "merged_at": util.datetime_utcnow_iso()})
        logger.info(
            "federation %s: merged %d tasks of action %s into job %s "
            "on pool %s", federation_id, added, action_id, job.id,
            pool_id)
        return True

    # ------------------------- elastic actions -------------------------

    def evaluate_elastic(self, federation_id: str,
                         fed: dict) -> int:
        """Cross-pool elasticity pass: for every PLACED job, migrate
        gangs that are starved on their pool — preempted/evicted/
        pending past the grace window, or stranded by total capacity
        loss — onto a sibling pool that satisfies the job's recorded
        constraints and its gang-size floor. Elasticity inside a pool
        (the agent's resize paths) always gets first refusal: a pool
        whose live capacity still covers min_instances is never
        migrated away from. Returns the number of jobs migrated."""
        epoch = self._elastic_epoch(federation_id)
        if epoch is None:
            return 0  # another evaluator leads this federation
        rows = [r for r in self.store.query_entities(
                    names.TABLE_FEDJOBS, partition_key=federation_id)
                if not r["_rk"].startswith("zap$")]
        if not rows:
            return 0
        facts = {}
        for pool_id in fed.get("pools", []):
            fact = _pool_facts(self.store, pool_id,
                               stale_seconds=self.node_stale_seconds)
            if fact is not None:
                facts[pool_id] = fact
        migrated = 0
        for row in rows:
            # Fencing re-check before each job's decision: the fact
            # gathering above can outlive the term, and a migration
            # fired on a stale verdict double-fans the gang.
            if not self._elastic_lease(federation_id).fenced(epoch):
                return migrated
            try:
                migrated += self._maybe_migrate_job(federation_id,
                                                    row, facts,
                                                    epoch)
            except Exception:
                logger.exception(
                    "elastic evaluation of job %s failed",
                    row["_rk"])
        return migrated

    def _elastic_lease(self,
                       federation_id: str
                       ) -> state_leases.LeaderLease:
        lease = self._elastic_leases.get(federation_id)
        if lease is None:
            scope = f"fed-{federation_id}"
            lease = state_leases.LeaderLease(
                self.store,
                key=names.leader_lease_key(
                    scope, state_leases.ROLE_FED_ELASTIC),
                epoch_key=names.leader_epoch_key(
                    scope, state_leases.ROLE_FED_ELASTIC),
                owner=self.owner,
                duration_seconds=max(2.0, 4.0 * self.poll_interval))
            self._elastic_leases[federation_id] = lease
        return lease

    def _elastic_epoch(self, federation_id: str) -> Optional[int]:
        """Leadership gate for one federation's elastic pass: the
        term's fencing epoch while this processor holds the
        ``fed-elastic`` lease, None otherwise (the sweep-lease
        protocol of agent/node_agent.py, federation-scoped)."""
        try:
            return self._elastic_lease(federation_id).epoch()
        except Exception:  # noqa: BLE001 - store hiccup = not leader
            logger.debug("elastic lease check failed for %s",
                         federation_id, exc_info=True)
            return None

    def _maybe_migrate_job(self, federation_id: str, row: dict,
                           facts: dict,
                           leader_epoch: Optional[int] = None) -> int:
        job_id = row["_rk"]
        src = row.get("pool_id")
        src_fact = facts.get(src)
        live = src_fact["nodes_live"] if src_fact else 0
        try:
            tasks = jobs_mgr.list_tasks(self.store, src, job_id)
        except Exception:  # noqa: BLE001 - pool/job may be mid-GC
            return 0
        starved_since: Optional[float] = None
        required = 0
        now = util.utcnow().timestamp()
        for task in tasks:
            state = task.get("state")
            if state in names.TERMINAL_TASK_STATES:
                continue
            spec = task.get("spec") or {}
            mi = spec.get("multi_instance") or {}
            size = int(mi.get("num_instances") or 1)
            if size <= 1:
                continue  # gang migration only (this evaluator)
            floor = int(mi.get("min_instances") or size)
            if live >= floor:
                continue  # in-pool elastic resize can still win
            if state in ("assigned", "running"):
                # Stranded mid-run: only reclaimable when the WHOLE
                # pool is dead (no live node could still be running a
                # member whose results we would orphan). The reclaim
                # stamps requeued_at; the grace clock below runs from
                # it, so migration follows on a later pass.
                if live == 0:
                    self._reclaim_stranded_task(src, task)
                continue
            if state not in names.CLAIMABLE_TASK_STATES:
                continue
            since = _iso_epoch(task.get("requeued_at")
                               or task.get("submitted_at"))
            if since is None or \
                    now - since < self.elastic_grace_seconds:
                continue
            required = max(required, floor)
            starved_since = (since if starved_since is None
                             else min(starved_since, since))
        if not required or starved_since is None:
            return 0
        constraints = dict(row.get("constraints") or {})
        if (constraints.get("required_target") or {}).get("pool_id"):
            return 0  # operator pinned the pool; never migrate
        candidates = [f for p, f in facts.items() if p != src]
        eligible = filter_pools_hard_constraints(candidates,
                                                 constraints)
        eligible = filter_pool_nodes(eligible, constraints,
                                     required_nodes=required)
        # Migration needs capacity NOW: an autoscale-pending bin is a
        # bet, and the job already lost one.
        eligible = [f for f in eligible
                    if not f.get("via_autoscale")
                    and f["nodes_live"] >= required]
        choice = greedy_best_fit(eligible)
        if choice is None:
            logger.info(
                "federation %s: job %s starved on %s (live=%d < "
                "floor=%d) but no sibling pool qualifies",
                federation_id, job_id, src, live, required)
            return 0
        return self._migrate_starved_job(
            federation_id, row, src, choice["pool_id"],
            starved_since, leader_epoch)

    def _reclaim_stranded_task(self, pool_id: str,
                               task: dict) -> None:
        """Reset a task stranded on an all-dead pool to pending
        (etag-guarded — exactly one evaluator wins), stamping
        requeued_at so the starvation grace clock starts now."""
        try:
            self.store.merge_entity(
                names.TABLE_TASKS, task["_pk"], task["_rk"],
                {"state": "pending", "node_id": None,
                 "requeued_at": util.datetime_utcnow_iso()},
                if_match=task["_etag"])
            logger.warning(
                "federation: reclaimed task %s/%s stranded on dead "
                "pool %s", task["_pk"], task["_rk"], pool_id)
        except (EtagMismatchError, NotFoundError):
            pass  # a peer evaluator (or the task itself) moved first

    def _migrate_starved_job(self, federation_id: str, row: dict,
                             src: str, dst: str,
                             starved_since: float,
                             leader_epoch: Optional[int] = None,
                             ) -> int:
        """Atomically re-target one job: claim the locator row first
        (etag-guarded merge — a concurrent evaluator loses cleanly),
        then disable -> migrate -> enable through the jobs manager,
        carry the compile-cache seed across, and price/trace the
        migration window. Task entities move verbatim, so checkpoint
        references in specs and the submission's trace ids survive —
        one trace spans the migration."""
        job_id = row["_rk"]
        try:
            job_entity = jobs_mgr.get_job(self.store, src, job_id)
        except jobs_mgr.JobNotFoundError:
            return 0
        # Claim the move WITHOUT re-pointing the locator yet: a
        # migration that fails mid-flight (a src agent claimed a task
        # in the race window, a transient store error) must leave the
        # locator still naming the pool that actually holds the job,
        # or every later evaluator pass would look for it in the
        # wrong place forever.
        try:
            self.store.merge_entity(
                names.TABLE_FEDJOBS, federation_id, job_id,
                {"migrating_to": dst,
                 "migrated_at": util.datetime_utcnow_iso(),
                 # The elastic term's fencing epoch: a deposed
                 # evaluator's stale claim is attributable, and its
                 # etag merge loses cleanly to the successor's.
                 "leader_epoch": leader_epoch},
                if_match=row["_etag"])
        except (EtagMismatchError, NotFoundError):
            return 0  # another evaluator/replica claimed the move
        disabled = False
        moved = None
        try:
            if job_entity.get("state") == "active":
                jobs_mgr.disable_job(self.store, src, job_id)
                disabled = True
            moved = jobs_mgr.migrate_job(self.store, src, job_id,
                                         dst)
            jobs_mgr.enable_job(self.store, dst, job_id)
        except Exception:
            logger.exception(
                "federation %s: migration of job %s %s -> %s failed "
                "mid-flight; rolling back for a later retry",
                federation_id, job_id, src, dst)
            if moved is None and disabled:
                # The job never left the source: re-enable it there
                # (best-effort — a failure here just leaves it
                # disabled until the operator or the next pass acts).
                try:
                    jobs_mgr.enable_job(self.store, src, job_id)
                except Exception:  # noqa: BLE001 - rollback is
                    # best-effort by design
                    logger.exception(
                        "federation %s: re-enable of job %s on %s "
                        "failed during rollback", federation_id,
                        job_id, src)
            try:
                # Release the claim so a later pass can retry. When
                # the tasks DID move but enable failed, point the
                # locator at the destination anyway — that is where
                # the job now lives.
                self.store.merge_entity(
                    names.TABLE_FEDJOBS, federation_id, job_id,
                    ({"pool_id": dst, "migrated_from": src,
                      "migrating_to": None}
                     if moved is not None
                     else {"migrating_to": None}))
            except Exception:  # noqa: BLE001 - locator repair is
                # best-effort; GC/retry reconciles
                logger.exception(
                    "federation %s: locator repair for job %s "
                    "failed", federation_id, job_id)
            return 0
        # Success: re-point the locator (we hold the claim — the
        # migrating_to stamp — so no concurrent evaluator writes it).
        self.store.merge_entity(
            names.TABLE_FEDJOBS, federation_id, job_id,
            {"pool_id": dst, "migrated_from": src,
             "migrating_to": None})
        self._carry_compile_cache(src, dst)
        now = util.utcnow().timestamp()
        from batch_shipyard_tpu.goodput import events as gp_events
        from batch_shipyard_tpu.trace import context as trace_ctx
        from batch_shipyard_tpu.trace import spans as trace_spans
        ctx = trace_ctx.TraceContext.from_entity(job_entity)
        # Priced on the DESTINATION pool: that is where the resumed
        # run's report is read, and the interval has fully elapsed
        # (starved -> re-targeted) so nothing is future-dated.
        gp_events.emit(
            self.store, dst, gp_events.GANG_MIGRATE, job_id=job_id,
            start=starved_since, end=now,
            attrs={"from_pool": src, "to_pool": dst,
                   "tasks": moved},
            trace_id=job_entity.get(trace_ctx.COL_TRACE_ID),
            span_id=job_entity.get(trace_ctx.COL_TRACE_SPAN))
        trace_spans.emit(
            self.store, dst, trace_spans.SPAN_GANG_MIGRATE, ctx,
            job_id=job_id, start=starved_since, end=now,
            attrs={"from_pool": src, "to_pool": dst,
                   "tasks": moved})
        logger.warning(
            "federation %s: migrated job %s from starved pool %s to "
            "%s (%d task(s), %.1fs starved)", federation_id, job_id,
            src, dst, moved, now - starved_since)
        return 1

    def _carry_compile_cache(self, src: str, dst: str) -> None:
        """Carry the source pool's compile-cache seed references to
        the destination: identities the destination has never seen
        get the tar copied and the dst latest.json pointed at it, so
        the migrated gang compiles warm on arrival. Best-effort by
        design — a failed carry costs one cold compile, never the
        migration."""
        from batch_shipyard_tpu.compilecache import (
            seeding as cc_seeding)
        try:
            src_latest = cc_seeding.latest_info(self.store, src)
            if not src_latest:
                return
            dst_latest = (cc_seeding.latest_info(self.store, dst)
                          or {"identities": {}})
            for identity, record in sorted(
                    (src_latest.get("identities") or {}).items()):
                if identity in dst_latest["identities"]:
                    continue  # dst already has a seed; never clobber
                src_key = record.get("key") or \
                    names.compile_cache_key(src, identity)
                dst_key = names.compile_cache_key(dst, identity)
                self.store.put_object(
                    dst_key, self.store.get_object(src_key))
                cc_seeding._update_latest(
                    self.store, dst, identity, {
                        **{k: v for k, v in record.items()},
                        "key": dst_key, "migrated_from": src})
                logger.info(
                    "carried compile-cache seed %s: pool %s -> %s",
                    identity, src, dst)
        except Exception:  # noqa: BLE001 - warm start is optional
            logger.warning("compile-cache carry %s -> %s failed",
                           src, dst, exc_info=True)

    def run(self) -> None:
        while not self.stop_event.is_set():
            try:
                self.process_once()
            except Exception:
                logger.exception("federation processing error")
            if self.stop_event.wait(self.poll_interval):
                break
        if self._lease is not None:
            try:
                self.store.release_lease(self._lease)
            except Exception:
                pass
        for lease in self._elastic_leases.values():
            try:
                lease.release()
            except Exception:  # noqa: BLE001 - expiry reclaims
                pass

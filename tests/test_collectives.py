"""Collective microbench sanity on the virtual CPU mesh (the mpiBench
recipe analog must run anywhere)."""

import jax.numpy as jnp

from batch_shipyard_tpu.ops import collectives
from batch_shipyard_tpu.parallel import mesh as mesh_mod


def test_collective_bench_runs_all_ops():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    rows = collectives.run_collective_bench(
        mesh, axis="dp", sizes_bytes=(1 << 12,), dtype=jnp.float32)
    ops = {r["op"] for r in rows}
    assert ops == {"psum", "all_gather", "ppermute", "reduce_scatter"}
    for row in rows:
        assert row["seconds"] > 0
        assert row["algo_bw_gbps"] > 0


def test_collective_correctness():
    """The timed functions must also be *correct* collectives."""
    import numpy as np
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    x = jnp.arange(8 * 128, dtype=jnp.float32)
    psum_fn = collectives._collective_fn(mesh, "dp", "psum")
    out = psum_fn(x)
    # Each shard contributes its slice; psum over 8 shards of the
    # sharded input returns sum of shards, replicated.
    expected = np.asarray(x).reshape(8, 128).sum(axis=0)
    np.testing.assert_allclose(np.asarray(out), expected)

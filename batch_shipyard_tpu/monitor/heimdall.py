"""Heimdall: monitoring service discovery daemon.

Reference analog: heimdall/heimdall.py — polls the monitoring table
for registered pools/fs-clusters, resolves node IPs via Batch/ARM
APIs (:292/:461), and writes Prometheus file_sd target JSON
(:416/:562). Ours resolves from TABLE_NODES/TABLE_MONITOR in the state
store and writes the same file_sd format, so a stock Prometheus
pointed at the output directory scrapes every registered resource's
node_exporter/cadvisor endpoints.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def add_pool_to_monitor(store: StateStore, pool_id: str,
                        node_exporter_port: int = 9100,
                        cadvisor_port: Optional[int] = None) -> None:
    """Register a pool for monitoring (monitor add analog,
    storage.add_resources_to_monitor storage.py:491)."""
    store.upsert_entity(names.TABLE_MONITOR, "monitor",
                        f"pool${pool_id}", {
                            "kind": "pool", "pool_id": pool_id,
                            "node_exporter_port": node_exporter_port,
                            "cadvisor_port": cadvisor_port,
                            "registered_at": util.datetime_utcnow_iso(),
                        })


def add_remotefs_to_monitor(store: StateStore, cluster_id: str,
                            node_exporter_port: int = 9100) -> None:
    store.upsert_entity(names.TABLE_MONITOR, "monitor",
                        f"remotefs${cluster_id}", {
                            "kind": "remotefs",
                            "cluster_id": cluster_id,
                            "node_exporter_port": node_exporter_port,
                            "registered_at": util.datetime_utcnow_iso(),
                        })


def remove_resource_from_monitor(store: StateStore,
                                 resource_key: str) -> None:
    try:
        store.delete_entity(names.TABLE_MONITOR, "monitor",
                            resource_key)
    except NotFoundError:
        pass


def list_monitored_resources(store: StateStore) -> list[dict]:
    return list(store.query_entities(names.TABLE_MONITOR,
                                     partition_key="monitor"))


def build_file_sd_targets(store: StateStore) -> list[dict]:
    """Resolve every registered resource into Prometheus file_sd
    target groups (heimdall.py:416 analog)."""
    groups: list[dict] = []
    for resource in list_monitored_resources(store):
        if resource["kind"] == "pool":
            pool_id = resource["pool_id"]
            ne_targets, ca_targets = [], []
            for node in store.query_entities(names.TABLE_NODES,
                                             partition_key=pool_id):
                ip = node.get("internal_ip")
                if not ip:
                    continue
                if resource.get("node_exporter_port"):
                    ne_targets.append(
                        f"{ip}:{resource['node_exporter_port']}")
                if resource.get("cadvisor_port"):
                    ca_targets.append(
                        f"{ip}:{resource['cadvisor_port']}")
            if ne_targets:
                groups.append({
                    "targets": sorted(ne_targets),
                    "labels": {"job": "node_exporter",
                               "shipyard_pool": pool_id}})
            if ca_targets:
                groups.append({
                    "targets": sorted(ca_targets),
                    "labels": {"job": "cadvisor",
                               "shipyard_pool": pool_id}})
        elif resource["kind"] == "remotefs":
            cluster_id = resource["cluster_id"]
            targets = []
            for row in store.query_entities(
                    names.TABLE_REMOTEFS_NODES, partition_key=cluster_id):
                ip = row.get("internal_ip")
                if ip:
                    targets.append(
                        f"{ip}:{resource['node_exporter_port']}")
            if targets:
                groups.append({
                    "targets": sorted(targets),
                    "labels": {"job": "node_exporter",
                               "shipyard_remotefs": cluster_id}})
    return groups


def write_file_sd(store: StateStore, output_dir: str) -> str:
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, "shipyard_targets.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(build_file_sd_targets(store), fh, indent=2)
    os.replace(tmp, path)
    return path


# The gauge export re-sweeps the event log every poll; bound the scan
# to a trailing day so cost tracks recent activity, not fleet age
# (operators prune history with `goodput prune` / events.prune).
GOODPUT_EXPORT_WINDOW_SECONDS = 24 * 3600.0

# Node health/quarantine gauges — and every OTHER per-node gauge
# (last-step-time) or node-attributed export (serving latency
# buckets) — only cover rows seen within this window (heartbeat, or
# registration for a still-booting node): generous against any sane
# heartbeat interval, small enough that a permanently crashed node
# stops gauging within minutes. A crashed replica must not export
# frozen percentiles forever.
NODE_GAUGE_STALE_SECONDS = 300.0


def _node_fresh(node: dict, now: float) -> bool:
    """THE staleness rule for per-node exports (shared by the
    health/quarantine gauges, the last-step-time gauges and the
    serving-latency attribution): row not offline and seen within
    NODE_GAUGE_STALE_SECONDS (heartbeat, or registration for a
    still-booting node)."""
    if node.get("state") == "offline":
        return False
    last_seen = float(node.get("heartbeat_at", 0) or 0)
    if last_seen <= 0:
        last_seen = float(node.get("registered_at", 0) or 0)
    return now - last_seen <= NODE_GAUGE_STALE_SECONDS


def build_goodput_metrics(store: StateStore) -> list[str]:
    """Prometheus gauge lines for every registered-or-known pool's
    goodput decomposition: goodput_ratio{pool=...} and
    badput_seconds{pool=...,category=...} (plus productive seconds),
    computed from the TABLE_GOODPUT event log over the trailing
    export window."""
    from batch_shipyard_tpu.goodput import accounting
    lines = [
        "# HELP goodput_ratio Fraction of wall-clock producing "
        "useful progress (availability x resource x program).",
        "# TYPE goodput_ratio gauge",
        "# HELP badput_seconds Unproductive wall-clock seconds by "
        "category.",
        "# TYPE badput_seconds gauge",
        "# HELP goodput_productive_seconds Wall-clock seconds of "
        "fresh training/serving progress.",
        "# TYPE goodput_productive_seconds gauge",
        "# HELP goodput_overlapped_seconds Background work (async "
        "checkpoint persist) not covered by productive windows; "
        "shown, not charged as badput.",
        "# TYPE goodput_overlapped_seconds gauge",
        "# HELP goodput_compile_saved_seconds Wall-clock seconds the "
        "warm persistent compilation cache avoided spending on "
        "compiles (compilecache/; not badput).",
        "# TYPE goodput_compile_saved_seconds gauge",
        "# HELP node_health_score Per-node health score in [0,1] "
        "(task failures/wedges decay it; below threshold the node "
        "quarantines itself and stops claiming).",
        "# TYPE node_health_score gauge",
        "# HELP nodes_quarantined Count of self-quarantined "
        "(auto-drained) nodes per pool.",
        "# TYPE nodes_quarantined gauge",
        "# HELP shipyard_serving_ttft_ms Serving time-to-first-token "
        "histogram over the trailing 24h window, merged across the "
        "pool's live replicas (trace serve_request spans, drained "
        "mid-run by the agents; stale/offline nodes excluded). "
        "WINDOWED and SAMPLED: bucket counts can shrink as spans "
        "age out or are pruned — query the buckets directly "
        "(histogram_quantile over the raw series), not "
        "rate()/increase() — and replicas head-sample span detail "
        "(first 512 requests, then 1-in-16), so counts are a sample. "
        "For exact, cumulative histograms scrape the "
        "replicas'/router's own /metrics.",
        "# TYPE shipyard_serving_ttft_ms histogram",
        "# HELP shipyard_serving_tpot_ms Serving time-per-output-"
        "token histogram over the trailing 24h window (same "
        "windowed semantics as shipyard_serving_ttft_ms).",
        "# TYPE shipyard_serving_tpot_ms histogram",
        "# HELP node_last_step_seconds Seconds per train step from "
        "the node's most recent step window (stale/offline nodes "
        "excluded).",
        "# TYPE node_last_step_seconds gauge",
        "# HELP shipyard_evictions_total Forcible evictions "
        "(victims hard-killed after ignoring their preempt notice "
        "past the grace window) over the trailing export window — "
        "WINDOWED like the serving histograms: counts shrink as "
        "events age out or are pruned. Events attributed to "
        "stale/offline nodes are excluded "
        "(NODE_GAUGE_STALE_SECONDS).",
        "# TYPE shipyard_evictions_total gauge",
        "# HELP shipyard_gang_migrations_total Cross-pool gang "
        "migrations (federation elastic re-targets) landing on this "
        "pool over the trailing export window (same windowed "
        "semantics).",
        "# TYPE shipyard_gang_migrations_total gauge",
        "# HELP shipyard_store_outage_seconds_total State-store "
        "outage seconds ridden out by the pool's resilient-store "
        "wrappers over the trailing export window (store_outage "
        "goodput intervals; WINDOWED — counts shrink as events age "
        "out or are pruned).",
        "# TYPE shipyard_store_outage_seconds_total gauge",
        "# HELP shipyard_task_adoptions_total Crash-restart "
        "adoptions (a restarted agent re-adopting its predecessor's "
        "still-running tasks) over the trailing export window (same "
        "windowed semantics; stale/offline-node events excluded).",
        "# TYPE shipyard_task_adoptions_total gauge",
        "# HELP shipyard_journal_backlog_entries Per-node "
        "resilient-store WAL backlog (advisory store ops journaled "
        "during an outage, awaiting replay) from the node's last "
        "heartbeat; stale/offline nodes excluded.",
        "# TYPE shipyard_journal_backlog_entries gauge",
        "# HELP shipyard_leader_epoch Current fencing epoch of each "
        "leader-gated sweep's named lease (state/leases.py): bumps "
        "once per leadership term, so a flapping value is a "
        "flapping leader.",
        "# TYPE shipyard_leader_epoch gauge",
    ]
    from batch_shipyard_tpu.goodput import events as goodput_events
    for pool in store.query_entities(names.TABLE_POOLS,
                                     partition_key="pools"):
        # One fetch per table per poll: node rows and the goodput
        # partition are each consumed by several exports below (the
        # pool report, the health gauges, the latency/step gauges) —
        # on a cloud store these are the two expensive scans.
        now = time.time()
        node_rows = list(store.query_entities(
            names.TABLE_NODES, partition_key=pool["_rk"]))
        events = goodput_events.query(store, pool["_rk"])
        report = accounting.pool_report(
            store, pool["_rk"],
            window_seconds=GOODPUT_EXPORT_WINDOW_SECONDS,
            include_jobs=False, event_list=events)
        lines.extend(accounting.prometheus_lines(
            report, {"pool": pool["_rk"]}))
        quarantined = 0
        for node in node_rows:
            # Dead or cleanly-stopped rows must not gauge (and alert)
            # forever: a crashed quarantined node would otherwise
            # inflate nodes_quarantined for the life of its row.
            if not _node_fresh(node, now):
                continue
            health = node.get(names.NODE_COL_HEALTH)
            if health is not None:
                lines.append(
                    f'node_health_score{{pool="{pool["_rk"]}",'
                    f'node="{node["_rk"]}"}} {float(health):.3f}')
            if node.get(names.NODE_COL_QUARANTINED):
                quarantined += 1
        lines.append(f'nodes_quarantined{{pool="{pool["_rk"]}"}} '
                     f'{quarantined}')
        lines.extend(_fleet_elasticity_metrics(pool["_rk"], now,
                                               node_rows, events))
        lines.extend(_control_plane_metrics(store, pool["_rk"], now,
                                            node_rows, events))
        lines.extend(_pool_latency_metrics(store, pool["_rk"], now,
                                           node_rows, events))
    lines.extend(_federation_lease_metrics(store))
    return lines


def _federation_lease_metrics(store: StateStore) -> list[str]:
    """The fed-elastic lease epoch per federation — the lease whose
    double-fire (a double-fanned gang migration) is the least
    idempotent of all the leader-gated sweeps, so its flapping signal
    matters most. Federation-scoped, not pool-scoped: exported once
    per federation row, alongside the pools' sweep leases."""
    from batch_shipyard_tpu.state import leases as state_leases
    lines: list[str] = []
    for fed in store.query_entities(names.TABLE_FEDERATIONS,
                                    partition_key="fed"):
        leader = state_leases.read_leader(
            store, names.leader_epoch_key(
                f"fed-{fed['_rk']}", state_leases.ROLE_FED_ELASTIC))
        if leader is None:
            continue
        lines.append(
            f'shipyard_leader_epoch'
            f'{{lease="{state_leases.ROLE_FED_ELASTIC}",'
            f'federation="{fed["_rk"]}"}} {int(leader["epoch"])}')
    return lines


def _control_plane_metrics(store: StateStore, pool_id: str,
                           now: float, node_rows: list[dict],
                           events: list[dict]) -> list[str]:
    """Control-plane health for one pool: outage seconds ridden out
    and adoptions performed (windowed, from the caller's
    already-fetched goodput events), per-node WAL backlog (from the
    heartbeat-published column), and each sweep lease's current
    fencing epoch (from its epoch object — one tiny metadata read
    per role per poll)."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    from batch_shipyard_tpu.state import leases as state_leases
    fresh = {node["_rk"] for node in node_rows
             if _node_fresh(node, now)}
    cutoff = now - GOODPUT_EXPORT_WINDOW_SECONDS
    outage_seconds = 0.0
    adoptions = 0
    for event in events:
        end = float(event.get("end", event.get("start", 0.0)))
        if end < cutoff:
            continue
        node_id = event.get("node_id")
        if node_id is not None and node_id not in fresh:
            continue
        kind = event.get("kind")
        if kind == goodput_events.STORE_OUTAGE:
            outage_seconds += max(
                0.0, end - float(event.get("start", end)))
        elif kind == goodput_events.TASK_ADOPTION:
            adoptions += 1
    lines = [
        f'shipyard_store_outage_seconds_total{{pool="{pool_id}"}} '
        f'{outage_seconds:.3f}',
        f'shipyard_task_adoptions_total{{pool="{pool_id}"}} '
        f'{adoptions}',
    ]
    for node in node_rows:
        if node["_rk"] not in fresh:
            continue
        backlog = node.get(names.NODE_COL_JOURNAL_BACKLOG)
        if backlog is None:
            continue
        lines.append(
            f'shipyard_journal_backlog_entries{{node="{node["_rk"]}"'
            f',pool="{pool_id}"}} {int(backlog)}')
    for role in state_leases.AGENT_LEADER_ROLES:
        leader = state_leases.read_leader(
            store, names.leader_epoch_key(pool_id, role))
        if leader is None:
            continue
        lines.append(
            f'shipyard_leader_epoch{{lease="{role}",'
            f'pool="{pool_id}"}} {int(leader["epoch"])}')
    return lines


def _fleet_elasticity_metrics(pool_id: str, now: float,
                              node_rows: list[dict],
                              events: list[dict]) -> list[str]:
    """Eviction/migration counters for one pool over the trailing
    export window. The per-pool eviction/migration badput-SECONDS
    ride the standard badput_seconds{category=...} gauges
    (accounting.prometheus_lines — the new categories are part of
    the partition); these counters answer the operator's other
    question: how OFTEN is the escalation ladder firing, and how
    often do gangs leave/arrive by migration. Node-attributed events
    honor the NODE_GAUGE_STALE_SECONDS rule like every other
    per-node export."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    fresh = {node["_rk"] for node in node_rows
             if _node_fresh(node, now)}
    cutoff = now - GOODPUT_EXPORT_WINDOW_SECONDS
    evictions = 0
    migrations = 0
    for event in events:
        if float(event.get("end", event.get("start", 0.0))) < cutoff:
            continue
        node_id = event.get("node_id")
        if node_id is not None and node_id not in fresh:
            continue
        kind = event.get("kind")
        if kind == goodput_events.TASK_EVICTED:
            evictions += 1
        elif kind == goodput_events.GANG_MIGRATE:
            migrations += 1
    return [
        f'shipyard_evictions_total{{pool="{pool_id}"}} {evictions}',
        f'shipyard_gang_migrations_total{{pool="{pool_id}"}} '
        f'{migrations}',
    ]


def _pool_latency_metrics(store: StateStore, pool_id: str,
                          now: float, node_rows: list[dict],
                          events: list[dict]) -> list[str]:
    """Serving latency histogram buckets + per-node last-step-time
    gauges for one pool, sourced from the trace log and the caller's
    already-fetched node rows + goodput events, over the trailing
    export window.

    Both honor the NODE_GAUGE_STALE_SECONDS rule: a serve span or
    step window attributed to a node whose row went stale/offline is
    dropped, so a crashed replica cannot export frozen percentiles
    (or a frozen step time) forever. Spans without a node id (e.g.
    dev-box ingests) have no row to go stale and pass through."""
    from batch_shipyard_tpu.trace import spans as trace_spans
    from batch_shipyard_tpu.trace.histogram import LatencyHistogram
    fresh = {node["_rk"] for node in node_rows
             if _node_fresh(node, now)}
    cutoff = now - GOODPUT_EXPORT_WINDOW_SECONDS

    def node_ok(row: dict) -> bool:
        node_id = row.get("node_id")
        return node_id is None or node_id in fresh

    lines: list[str] = []
    ttft = LatencyHistogram()
    tpot = LatencyHistogram()
    for row in trace_spans.query(store, pool_id):
        if row.get("kind") != trace_spans.SPAN_SERVE_REQUEST:
            continue
        if float(row.get("end", 0.0)) < cutoff or not node_ok(row):
            continue
        attrs = row.get("attrs") or {}
        try:
            ttft.observe(float(attrs["ttft_ms"]))
            tpot.observe(float(attrs["tpot_ms"]))
        except (KeyError, TypeError, ValueError):
            continue
    for metric, hist in (("ttft_ms", ttft), ("tpot_ms", tpot)):
        if hist.count:
            lines.extend(hist.prometheus_bucket_lines(
                f"shipyard_serving_{metric}", {"pool": pool_id}))
    # Latest step window per node -> seconds-per-step gauge (the
    # liveness-of-progress signal next to the health score).
    from batch_shipyard_tpu.goodput import events as goodput_events
    latest: dict[str, tuple[float, float]] = {}
    for event in events:
        if event.get("kind") != goodput_events.PROGRAM_STEP_WINDOW:
            continue
        node_id = event.get("node_id")
        if node_id is None or node_id not in fresh:
            continue
        end = float(event.get("end", 0.0))
        if end < cutoff:
            continue
        attrs = event.get("attrs") or {}
        try:
            steps = int(attrs["step_end"]) - int(attrs["step_start"])
        except (KeyError, TypeError, ValueError):
            continue
        if steps <= 0:
            continue
        seconds = max(0.0, end - float(event.get("start", end)))
        if node_id not in latest or end > latest[node_id][0]:
            latest[node_id] = (end, seconds / steps)
    for node_id in sorted(latest):
        lines.append(
            f'node_last_step_seconds{{node="{node_id}",'
            f'pool="{pool_id}"}} {latest[node_id][1]:.6f}')
    return lines


def write_goodput_metrics(store: StateStore, output_dir: str) -> str:
    """Write the goodput gauges as a node_exporter textfile-collector
    .prom (the same atomic tmp+rename discipline as file_sd), so a
    Prometheus already scraping heimdall's targets picks the fleet's
    productivity up with zero extra configuration."""
    os.makedirs(output_dir, exist_ok=True)
    path = os.path.join(output_dir, "shipyard_goodput.prom")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write("\n".join(build_goodput_metrics(store)) + "\n")
    os.replace(tmp, path)
    return path


def run_daemon(store: StateStore, output_dir: str,
               poll_interval: float = 15.0,
               stop_event: Optional[threading.Event] = None) -> None:
    """Discovery loop: refresh file_sd targets + goodput gauges until
    stopped."""
    stop = stop_event or threading.Event()
    while True:
        try:
            write_file_sd(store, output_dir)
        except Exception:
            logger.exception("heimdall refresh failed")
        try:
            write_goodput_metrics(store, output_dir)
        except Exception:
            logger.exception("heimdall goodput export failed")
        if stop.wait(poll_interval):
            return

"""DiT denoising-diffusion training payload + few-step DDIM sampling
(the generative-vision workload; the reference runs such jobs only as
opaque framework containers, /root/reference/recipes/Chainer-GPU).

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.train_diffusion \
        --batch-per-device 64 --steps 50 --sample 4
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu import compilecache
from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.models import diffusion as dif_mod
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod
from batch_shipyard_tpu.workloads import checkpoint
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-per-device", type=int, default=64)
    parser.add_argument("--image-size", type=int, default=32)
    parser.add_argument("--patch-size", type=int, default=4)
    parser.add_argument("--d-model", type=int, default=512)
    parser.add_argument("--layers", type=int, default=8)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--num-classes", type=int, default=None)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--warmup", type=int, default=3)
    parser.add_argument("--sample", type=int, default=0,
                        help="generate N DDIM samples at the end")
    parser.add_argument("--sample-steps", type=int, default=50)
    checkpoint.add_checkpoint_args(parser)
    compilecache.add_compile_cache_args(parser)
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    batch_size = args.batch_per_device * n_dev
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = dif_mod.DiTConfig(
        image_size=args.image_size, patch_size=args.patch_size,
        d_model=args.d_model, n_layers=args.layers,
        n_heads=args.heads, d_ff=4 * args.d_model,
        num_classes=args.num_classes, dtype=jnp.bfloat16)
    compilecache.enable_from_args(
        args, mesh_shape=dict(mesh.shape),
        model_digest=compilecache.config_digest(config))
    harness = train_mod.build_diffusion_train(
        mesh, config, batch_size=batch_size)
    join_aot = (compilecache.aot.precompile_async(harness)
                if args.aot_precompile else None)
    from batch_shipyard_tpu.data import loader

    rng = np.random.RandomState(jax.process_index())
    local_batch = batch_size // jax.process_count()
    batch = {"images": np.tanh(
        rng.randn(local_batch, args.image_size, args.image_size,
                  3)).astype(np.float32)}
    if args.num_classes:
        batch["labels"] = rng.randint(
            0, args.num_classes, (local_batch,)).astype(np.int32)
    batch = loader.place_global(batch, harness.batch_sharding)
    params, opt_state = harness.params, harness.opt_state
    ckpt = checkpoint.TrainCheckpointer.from_args(args)
    params, opt_state, start_step = ckpt.restore(params, opt_state)
    if start_step:
        distributed.log(ctx, f"resumed from step {start_step}")
    if join_aot is not None:
        join_aot()
    for _ in range(args.warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        float(metrics["loss"])  # hard sync
    # On-demand profiling: `shipyard jobs profile` (trace/profiling).
    from batch_shipyard_tpu.trace.profiling import StepProfiler
    profiler = StepProfiler()
    start = time.perf_counter()
    for step_num in range(start_step, start_step + args.steps):
        profiler.tick(step_num)
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        # Cooperative preemption: force-commit this boundary and exit
        # with the distinct preempted status (requeued at full
        # budget; the rerun resumes here).
        if ckpt.maybe_preempt(step_num + 1, params, opt_state):
            profiler.close()
            return preemption.EXIT_PREEMPTED
        ckpt.step_save(step_num + 1, params, opt_state)
    loss = float(metrics["loss"])
    profiler.close()
    elapsed = time.perf_counter() - start
    ckpt.finalize(start_step + args.steps, params, opt_state)
    images_per_sec = batch_size * args.steps / elapsed
    distributed.log(ctx, (
        f"dit: mesh={dict(mesh.shape)} {images_per_sec:.1f} img/s "
        f"total, loss={loss:.4f}"))
    if args.sample and jax.process_count() > 1:
        # Params span non-addressable devices on a multi-host pod; the
        # single-process eager sampler below cannot run there (it
        # would crash on process 0 and deadlock the others).
        distributed.log(ctx, "ddim sampling skipped on multi-host "
                             "runs; sample from a restored checkpoint")
    elif args.sample:
        model = dif_mod.DiT(config)
        labels = (jnp.zeros((args.sample,), jnp.int32)
                  if args.num_classes else None)
        samples = dif_mod.ddim_sample(
            model, params, jax.random.PRNGKey(0), args.sample,
            num_steps=args.sample_steps, labels=labels)
        arr = np.asarray(samples)
        distributed.log(ctx, (
            f"ddim samples: shape={arr.shape} "
            f"range=[{arr.min():.3f}, {arr.max():.3f}]"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

from batch_shipyard_tpu.config.validator import (  # noqa: F401
    ConfigType,
    ValidationError,
    validate_config,
)

"""Self-healing execution layer tests (PR 5): deterministic chaos
plans, the wedge watchdog, the backoff/quarantine retry supervisor,
node health scoring, and checkpoint-aware gang requeue.

All CPU-only fakepod pools; every wait is poll-with-deadline (no
fixed sleeps beyond sub-second task payloads) so the suite stays
cheap under container load."""

import json
import os
import signal
import time

import pytest

from batch_shipyard_tpu.chaos.plan import ChaosPlan
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})

# Fast supervisor settings for every pool in this file: sub-second
# backoff so retried tasks re-run promptly.
FAST_RETRY = {"retry_backoff_base": 0.2, "retry_backoff_cap": 1.0}


def _make_pool(pool_id: str, accelerator: str = "v5litepod-8",
               slots: int = 2, stale: float = 3.0,
               agent_kwargs: dict = FAST_RETRY):
    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": accelerator},
        "task_slots_per_node": slots,
        "max_wait_time_seconds": 30}}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, node_stale_seconds=stale)
    substrate.agent_kwargs = dict(agent_kwargs)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return store, substrate, pool


def _poll(predicate, timeout: float, interval: float = 0.1,
          message: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


# ------------------------------ plans ----------------------------------

def test_chaos_plan_same_seed_same_schedule():
    """Determinism acceptance: two plans from one seed inject
    identically (fingerprint equality), different seeds differ, and
    a plan round-trips through its dict serialization."""
    a = ChaosPlan.generate(7, duration=10.0, num_nodes=4)
    b = ChaosPlan.generate(7, duration=10.0, num_nodes=4)
    assert a.fingerprint() == b.fingerprint()
    assert a.injections == b.injections
    assert a.fingerprint() != ChaosPlan.generate(8).fingerprint()
    rt = ChaosPlan.from_dict(json.loads(json.dumps(a.to_dict())))
    assert rt.fingerprint() == a.fingerprint()
    # Schedule sanity: every injection lands inside the drill window
    # with runway on both sides, sorted by time.
    ats = [i.at for i in a.injections]
    assert ats == sorted(ats)
    assert all(0 < at < 10.0 for at in ats)


def test_chaos_plan_rejects_unknown_kind():
    with pytest.raises(ValueError):
        ChaosPlan.generate(0, kinds=("task_wedge", "bogus"))


# -------------------------- wedge watchdog -----------------------------

def test_wedge_watchdog_kills_and_retry_completes(tmp_path):
    """The TPU-wedge shape (TPU_WEDGE_REPORT.md): a task that stays
    alive but emits no progress beats is killed by the watchdog at
    its progress deadline, requeued with backoff, and completes on
    the retry — an unbounded hang became one bounded retry."""
    store, substrate, pool = _make_pool("wedgepool")
    marker = tmp_path / "attempted"
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "wedge",
            "tasks": [{"id": "t0",
                       # Attempt 1 wedges (no beats, long sleep);
                       # attempt 2 sees the marker and succeeds.
                       "command": (f"if [ -f {marker} ]; then "
                                   f"echo healed; else "
                                   f"touch {marker} && sleep 60; fi"),
                       "progress_deadline_seconds": 1,
                       "max_task_retries": 2}],
        }]})
        start = time.monotonic()
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "wedgepool", "wedge",
                                        timeout=30, poll_interval=0.2)
        elapsed = time.monotonic() - start
        assert tasks[0]["state"] == "completed"
        assert tasks[0]["retries"] == 1
        out = jobs_mgr.get_task_output(store, "wedgepool", "wedge",
                                       "t0")
        assert out.strip() == b"healed"
        # The wedge attempt is in the diagnostics history with its
        # watchdog reason, and the whole recovery beat the 60s hang
        # by an order of magnitude.
        history = tasks[0].get("attempt_history") or []
        assert any("wedged" in (a.get("reason") or "")
                   for a in history), history
        assert elapsed < 25, elapsed
    finally:
        substrate.stop_all()


def test_progress_beats_defeat_the_watchdog(tmp_path):
    """A task that keeps beating its progress file is NOT killed even
    though it runs far past the deadline — the watchdog measures
    progress staleness, not wall time."""
    store, substrate, pool = _make_pool("beatpool")
    try:
        # Beat every 0.5s for 3s against a 1s deadline.
        cmd = ("for i in 1 2 3 4 5 6; do "
               "touch $SHIPYARD_PROGRESS_FILE; sleep 0.5; done; "
               "echo steady")
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "beats",
            "tasks": [{"id": "t0", "command": cmd,
                       "progress_deadline_seconds": 1,
                       "max_task_retries": 1}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "beatpool", "beats",
                                        timeout=30, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        assert not tasks[0].get("retries")
        assert not tasks[0].get("wedged")
    finally:
        substrate.stop_all()


# ------------------------- retry supervisor ----------------------------

def test_retry_backoff_stamps_not_before(tmp_path):
    """A failed task requeues with an exponential-backoff not_before
    honored by the claim path: the retry never starts before it."""
    store, substrate, pool = _make_pool(
        "backoffpool",
        agent_kwargs={"retry_backoff_base": 0.8,
                      "retry_backoff_cap": 2.0})
    marker = tmp_path / "failed-once"
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "boff",
            "tasks": [{"id": "t0",
                       "command": (f"if [ -f {marker} ]; then "
                                   f"echo ok; else "
                                   f"touch {marker} && exit 1; fi"),
                       "max_task_retries": 3}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        # Catch the backoff window: retries bumped, not_before ahead.
        entity = _poll(
            lambda: (e := jobs_mgr.get_task(
                store, "backoffpool", "boff", "t0")).get("retries")
            and e, timeout=15, interval=0.05,
            message="first requeue")
        not_before = float(entity["not_before"])
        requeue_observed = time.time()
        assert entity["retries"] == 1
        assert entity["last_exit_code"] == 1
        # base 0.8 * 2^0 with +-25% jitter => [0.6, 1.0]s
        assert 0.0 < not_before - requeue_observed <= 1.1
        tasks = jobs_mgr.wait_for_tasks(store, "backoffpool", "boff",
                                        timeout=30, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        started_retry = tasks[0].get("started_at")
        assert started_retry is not None
        # The retry's start honored the backoff stamp.
        import datetime
        started_ts = datetime.datetime.strptime(
            started_retry, "%Y-%m-%dT%H:%M:%S.%fZ").replace(
            tzinfo=datetime.timezone.utc).timestamp()
        assert started_ts >= not_before - 0.25
    finally:
        substrate.stop_all()


def test_poison_quarantine_with_diagnostics():
    """Exhausting the retry budget parks the task in the quarantined
    terminal state with a post-mortem bundle: stderr tail, node id
    history, exit codes — surfaced by `shipyard jobs tasks list`."""
    store, substrate, pool = _make_pool("qpool")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "poison",
            "tasks": [{"id": "bad",
                       "command": ("echo boom-stderr >&2; exit 3"),
                       "max_task_retries": 1},
                      {"id": "good", "command": "echo fine"}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
            store, "qpool", "poison", timeout=30,
            poll_interval=0.2)}
        assert tasks["good"]["state"] == "completed"
        bad = tasks["bad"]
        assert bad["state"] == names.TASK_STATE_QUARANTINED
        assert bad["exit_code"] == 3
        assert "retry budget exhausted" in bad["error"]
        diag = bad["diagnostics"]
        assert "boom-stderr" in diag["stderr_tail"]
        history = diag["attempt_history"]
        assert [a["exit_code"] for a in history] == [3, 3]  # + 1 retry
        assert len(history) == 2
        assert all(a.get("node_id") for a in history)
        # The operator surface (jobs tasks list) projects the node /
        # exit-code histories from the stored attempt_history.
        from batch_shipyard_tpu import fleet as fleet_mod
        emitted = {}
        ctx = type("Ctx", (), {"store": store, "pool": pool})()
        orig = fleet_mod._emit
        fleet_mod._emit = lambda data, raw=False: emitted.update(data)
        try:
            fleet_mod.action_jobs_tasks_list(ctx, "poison", raw=True)
        finally:
            fleet_mod._emit = orig
        shown = {t["id"]: t for t in emitted["tasks"]}
        assert shown["bad"]["diagnostics"]["exit_codes"] == [3, 3]
        assert len(shown["bad"]["diagnostics"]["node_history"]) == 2
        # Quarantined is terminal for job rollups: stats count it and
        # the job autocompletes despite the poison task.
        stats = pool_mgr.pool_stats(store, "qpool")
        assert stats["tasks"][names.TASK_STATE_QUARANTINED] == 1
    finally:
        substrate.stop_all()


def test_zero_budget_task_fails_plain():
    """max_task_retries=0 (the default) keeps the legacy contract:
    a failing task lands in 'failed', not 'quarantined'."""
    store, substrate, pool = _make_pool("legacypool")
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "legacy",
            "tasks": [{"id": "t0", "command": "exit 7"}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "legacypool", "legacy",
                                        timeout=30, poll_interval=0.2)
        assert tasks[0]["state"] == "failed"
        assert tasks[0]["exit_code"] == 7
    finally:
        substrate.stop_all()


# ------------------------- node health score ---------------------------

def test_node_health_quarantine_and_recovery():
    """Repeated wedges decay a node's health score below the
    threshold: the node quarantines itself (claims refused, gang
    joins refused, columns published for observers), then recovers
    above the threshold after successes and claims again."""
    store, substrate, pool = _make_pool("healthpool")
    try:
        agents = _poll(
            lambda: list(substrate._agents.get("healthpool",
                                               {}).values()),
            timeout=15, message="agents booted")
        agent = agents[0]
        assert not agent.node_quarantined()
        # Three wedges: 1.0 -> 0.5 -> 0.25 -> 0.125 < 0.25 threshold.
        for _ in range(3):
            agent._note_task_outcome(False, wedged=True)
        assert agent.node_quarantined()
        # Published on the node entity for claim-exclusion observers
        # (gang recovery target choice, heimdall gauges).
        node = _poll(
            lambda: (n := store.get_entity(
                names.TABLE_NODES, "healthpool",
                agent.identity.node_id)).get(
                names.NODE_COL_QUARANTINED) and n,
            timeout=10, message="quarantine column")
        assert node[names.NODE_COL_HEALTH] < 0.25
        # A quarantined node refuses new work on both claim paths.
        pk = names.task_pk("healthpool", "jx")
        store.insert_entity(names.TABLE_TASKS, pk, "tx",
                            {"state": "pending", "spec": {}})
        entity = store.get_entity(names.TABLE_TASKS, pk, "tx")
        assert agent._claim_regular("jx", "tx", entity) is None
        assert agent._gang_claim(
            names.gang_pk("healthpool", "jx", "tx"), 0) is False
        # Successes recover it past the threshold; claims resume.
        for _ in range(3):
            agent._note_task_outcome(True)
        assert not agent.node_quarantined()
        entity = store.get_entity(names.TABLE_TASKS, pk, "tx")
        assert agent._claim_regular("jx", "tx", entity) is not None
    finally:
        substrate.stop_all()


def test_node_quarantine_probation_release():
    """Quarantine is probational, never permanent: a quarantined node
    claims nothing, so it can never earn back its score through task
    successes — without the probation timer a poison job of ordinary
    failing tasks would auto-drain every node in the pool forever.
    After the window the node resumes claims at exactly the threshold
    score, where a single further failure re-quarantines it."""
    store, substrate, pool = _make_pool(
        "probation",
        agent_kwargs={**FAST_RETRY, "health_probation_seconds": 0.3})
    try:
        agents = _poll(
            lambda: list(substrate._agents.get("probation",
                                               {}).values()),
            timeout=15, message="agents booted")
        agent = agents[0]
        for _ in range(3):
            agent._note_task_outcome(False, wedged=True)
        assert agent.node_quarantined()
        _poll(lambda: not agent.node_quarantined(),
              timeout=10, message="probation release")
        # Claims resume after release.
        pk = names.task_pk("probation", "jp")
        store.insert_entity(names.TABLE_TASKS, pk, "tp",
                            {"state": "pending", "spec": {}})
        entity = store.get_entity(names.TABLE_TASKS, pk, "tp")
        assert agent._claim_regular("jp", "tp", entity) is not None
        # Probation means probation: one more failure at the
        # threshold score re-quarantines immediately.
        agent._note_task_outcome(False)
        assert agent.node_quarantined()
    finally:
        substrate.stop_all()


def test_beat_throttle_scales_to_deadline(tmp_path, monkeypatch):
    """A tight watchdog deadline must not be starved by the beat
    throttle itself: with $SHIPYARD_PROGRESS_DEADLINE exported the
    throttle shrinks to deadline/4, so a task that progresses every
    step always lands beats well inside its deadline."""
    from batch_shipyard_tpu.agent import progress as progress_mod
    path = tmp_path / "beat"
    monkeypatch.setenv(progress_mod.PROGRESS_FILE_ENV, str(path))
    monkeypatch.setenv(progress_mod.PROGRESS_DEADLINE_ENV, "1")
    assert progress_mod._throttle_seconds() == pytest.approx(0.25)
    progress_mod._last_beat_at = 0.0
    progress_mod.beat()
    first = path.stat().st_mtime
    # Inside BEAT_INTERVAL (would be dropped by the fixed throttle)
    # but past deadline/4: the beat must land (mtime advances — the
    # only signal the watchdog reads).
    time.sleep(0.3)
    progress_mod.beat()
    assert path.stat().st_mtime > first
    # Without the exported deadline the ceiling applies unchanged.
    monkeypatch.delenv(progress_mod.PROGRESS_DEADLINE_ENV)
    assert progress_mod._throttle_seconds() == progress_mod.BEAT_INTERVAL


def test_wedging_node_health_drops_e2e(tmp_path):
    """Acceptance: an injected wedge drops the wedging node's health
    score on its entity (the heimdall gauge source) while the task
    still completes through retry."""
    store, substrate, pool = _make_pool("wdgscore")
    marker = tmp_path / "once"
    try:
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "w",
            "tasks": [{"id": "t0",
                       "command": (f"if [ -f {marker} ]; then "
                                   f"echo done; else "
                                   f"touch {marker} && sleep 60; fi"),
                       "progress_deadline_seconds": 1,
                       "max_task_retries": 2}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(store, "wdgscore", "w",
                                        timeout=30, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        wedge_nodes = [a.get("node_id") for a in
                       tasks[0]["attempt_history"]
                       if "wedged" in (a.get("reason") or "")]
        assert wedge_nodes
        node = store.get_entity(names.TABLE_NODES, "wdgscore",
                                wedge_nodes[0])
        assert node[names.NODE_COL_HEALTH] < 1.0
    finally:
        substrate.stop_all()


# -------------------- checkpoint-aware gang requeue --------------------

def test_gang_member_killed_midrun_resumes_from_checkpoint(tmp_path):
    """Acceptance e2e: a gang losing a member mid-run (its process
    killed, the preemption shape) requeues within the retry budget
    and the rerun RESUMES from the committed checkpoint — the step
    counter strictly advances past the restored step instead of
    restarting from zero."""
    store, substrate, pool = _make_pool("gangpool",
                                        accelerator="v5litepod-16")
    ckpt = tmp_path / "ckpt"
    try:
        # Attempt 1: instance 0 commits step 3, then the gang
        # "trains" (sleeps) — one instance gets SIGKILLed mid-sleep.
        # Attempt 2: restore the committed step and advance strictly
        # past it. Only instance 0 touches the checkpoint (the
        # single-writer convention real save pipelines follow), so
        # there is no cross-instance write race.
        cmd = (f"step=$(cat {ckpt} 2>/dev/null || echo 0); "
               f"if [ \"$SHIPYARD_TASK_INSTANCE\" != \"0\" ]; then "
               f"sleep 3; "
               f"elif [ \"$step\" = \"0\" ]; then echo 3 > {ckpt}; "
               f"sleep 3; else echo $((step+2)) > {ckpt}; fi")
        jobs = settings_mod.job_settings_list({"job_specifications": [{
            "id": "gj",
            "tasks": [{"id": "g0", "command": cmd,
                       "max_task_retries": 2,
                       "multi_instance": {"num_instances": 2}}],
        }]})
        jobs_mgr.add_jobs(store, pool, jobs)

        def committed_and_running():
            procs = []
            for agent in substrate._agents.get("gangpool",
                                               {}).values():
                procs.extend(agent._live_procs.values())
            # Kill only after the checkpoint committed: the rerun
            # must have a restore point (real preemptions can land
            # earlier; then recovery replays from step 0 — fine, but
            # not the resume path this test pins down).
            return procs if len(procs) >= 2 and ckpt.exists() \
                else None

        procs = _poll(committed_and_running, timeout=20,
                      message="gang instances running past commit")
        os.killpg(os.getpgid(procs[0].pid), signal.SIGKILL)
        tasks = jobs_mgr.wait_for_tasks(store, "gangpool", "gj",
                                        timeout=40, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        assert tasks[0]["retries"] == 1
        # Strictly past the restored step: 3 (committed) -> 5.
        assert int(ckpt.read_text().strip()) == 5
        # The rerun's rendezvous used a fresh attempt-namespaced gang
        # partition and everything was cleaned up.
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_broken_gang_requeues_within_budget():
    """A gang with a dead member (stale heartbeat, the preempted-node
    shape) and retry budget left is REQUEUED by the surviving
    observers — not failed terminally — and the rerun completes on
    healthy nodes."""
    store, substrate, pool = _make_pool("grec")
    pk = names.task_pk("grec", "jg")
    store.insert_entity(names.TABLE_JOBS, "grec", "jg",
                        {"state": "active", "spec": {}})
    spec = {"command": "echo recovered", "runtime": "none",
            "max_task_retries": 1,
            "multi_instance": {"num_instances": 2,
                               "jax_distributed": {"enabled": False}}}
    try:
        store.insert_entity(names.TABLE_TASKS, pk, "g0",
                            {"state": "running", "spec": spec,
                             "retries": 0})
        # Ghost member holds instance 0 of attempt 0 on a dead node
        # (no heartbeat, no registration grace).
        gang_pk = names.gang_pk("grec", "jg", "g0")
        store.insert_entity(names.TABLE_GANGS, gang_pk, "i0", {
            "node_id": "ghost", "hostname": "ghost",
            "internal_ip": "10.9.9.9", "slice_index": 0,
            "worker_index": 0, "state": "joined"})
        store.insert_entity(names.TABLE_GANGS, gang_pk,
                            "node$ghost", {"instance": 0})
        store.upsert_entity(names.TABLE_NODES, "grec", "ghost", {
            "state": "running", "heartbeat_at": 0.0})
        for k in range(2):
            store.put_message(
                names.task_queue("grec"),
                json.dumps({"job_id": "jg", "task_id": "g0",
                            "instance": k}).encode())
        tasks = jobs_mgr.wait_for_tasks(store, "grec", "jg",
                                        timeout=40, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        assert tasks[0]["retries"] == 1
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_broken_gang_budget_exhausted_quarantines():
    """A broken gang past its retry budget lands in quarantine with
    the diagnostics bundle naming the lost nodes."""
    store, substrate, pool = _make_pool("gquar")
    pk = names.task_pk("gquar", "jq")
    store.insert_entity(names.TABLE_JOBS, "gquar", "jq",
                        {"state": "active", "spec": {}})
    spec = {"command": "echo never", "runtime": "none",
            "max_task_retries": 1,
            "multi_instance": {"num_instances": 8,
                               "jax_distributed": {"enabled": False}}}
    try:
        # retries == max_task_retries: the budget is already burned.
        store.insert_entity(names.TABLE_TASKS, pk, "g0",
                            {"state": "running", "spec": spec,
                             "retries": 1})
        gang_pk = names.gang_pk("gquar", "jq", "g0", attempt=1)
        store.insert_entity(names.TABLE_GANGS, gang_pk, "i0", {
            "node_id": "ghost", "hostname": "ghost",
            "internal_ip": "10.9.9.9", "slice_index": 0,
            "worker_index": 0, "state": "joined"})
        store.insert_entity(names.TABLE_GANGS, gang_pk,
                            "node$ghost", {"instance": 0})
        store.upsert_entity(names.TABLE_NODES, "gquar", "ghost", {
            "state": "running", "heartbeat_at": 0.0})
        store.put_message(
            names.task_queue("gquar"),
            json.dumps({"job_id": "jq", "task_id": "g0",
                        "instance": 1}).encode())
        tasks = jobs_mgr.wait_for_tasks(store, "gquar", "jq",
                                        timeout=40, poll_interval=0.2)
        assert tasks[0]["state"] == names.TASK_STATE_QUARANTINED
        assert "gang member(s) lost" in tasks[0]["error"]
        assert "ghost" in str(
            tasks[0]["diagnostics"]["attempt_history"])
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_abandoned_gang_claim_resumed_by_owner():
    """Regression: a worker slot that crashes AFTER _gang_claim (a
    store fault in the rendezvous loop — the chaos store_error shape)
    strands an i<k> row owned by a LIVE node. No observer ever judges
    it stale and no other node can insert over it, so before the
    resume path the gang wedged forever (drill timeout with the gang
    task stuck pending). The redelivered message must let the owning
    node resume its own abandoned claim and complete the gang."""
    store, substrate, pool = _make_pool("gresume")
    store.insert_entity(names.TABLE_JOBS, "gresume", "jr",
                        {"state": "active", "spec": {}})
    pk = names.task_pk("gresume", "jr")
    spec = {"command": "echo resumed", "runtime": "none",
            "max_task_retries": 1,
            "multi_instance": {"num_instances": 2,
                               "jax_distributed": {"enabled": False}}}
    try:
        store.insert_entity(names.TABLE_TASKS, pk, "g0",
                            {"state": "pending", "spec": spec,
                             "retries": 0})
        # Strand a live agent node's claim of instance 0 — the exact
        # rows a post-claim crash leaves behind.
        agent = next(iter(substrate._agents["gresume"].values()))
        gang_pk = names.gang_pk("gresume", "jr", "g0")
        store.insert_entity(names.TABLE_GANGS, gang_pk,
                            f"node${agent.identity.node_id}",
                            {"instance": 0})
        store.insert_entity(names.TABLE_GANGS, gang_pk, "i0", {
            "node_id": agent.identity.node_id,
            "hostname": agent.identity.hostname,
            "internal_ip": agent.identity.internal_ip,
            "slice_index": 0, "worker_index": 0,
            "state": "joined"})
        for k in range(2):
            store.put_message(
                names.task_queue("gresume"),
                json.dumps({"job_id": "jr", "task_id": "g0",
                            "instance": k}).encode())
        tasks = jobs_mgr.wait_for_tasks(store, "gresume", "jr",
                                        timeout=40, poll_interval=0.2)
        assert tasks[0]["state"] == "completed"
        assert not list(store.query_entities(names.TABLE_GANGS))
    finally:
        substrate.stop_all()


def test_gang_claim_resume_is_guarded():
    """_gang_claim resumes ONLY a claim that is ours, still 'joined',
    and not live in any worker slot of this process — a duplicate
    message copy or a finished member must keep bouncing."""
    store, substrate, pool = _make_pool("gguard")
    try:
        agent = next(iter(substrate._agents["gguard"].values()))
        me = agent.identity.node_id
        gang_pk = names.gang_pk("gguard", "jx", "g0")
        store.insert_entity(names.TABLE_GANGS, gang_pk,
                            f"node${me}", {"instance": 0})
        store.insert_entity(names.TABLE_GANGS, gang_pk, "i0",
                            {"node_id": me, "state": "joined"})
        # Abandoned (no slot holds it): resumed.
        assert agent._gang_claim(gang_pk, 0) is True
        # Now registered as live: a duplicate copy bounces.
        assert agent._gang_claim(gang_pk, 0) is False
        with agent._running_lock:
            agent._active_gang_claims.discard((gang_pk, 0))
        # A 'done' member is never resumed (the all-done probe path
        # finalizes on its behalf instead of re-running it).
        store.merge_entity(names.TABLE_GANGS, gang_pk, "i0",
                           {"state": "done"})
        assert agent._gang_claim(gang_pk, 0) is False
        # Another node's row is never resumable here.
        store.merge_entity(names.TABLE_GANGS, gang_pk, "i0",
                           {"node_id": "other", "state": "joined"})
        assert agent._gang_claim(gang_pk, 0) is False
    finally:
        substrate.stop_all()


# ----------------------- _node_alive grace window ----------------------

def test_node_alive_registration_grace():
    """Regression (satellite): a node entity registered but not yet
    heartbeating (heartbeat_at absent/0) is ALIVE within the
    staleness window of its registration — a gang observer must not
    fail a healthy just-booted member. Without registered_at (legacy
    rows) or past the window it is dead, as before."""
    store, substrate, pool = _make_pool("gracepool",
                                        accelerator="v5litepod-4")
    try:
        agents = _poll(
            lambda: list(substrate._agents.get("gracepool",
                                               {}).values()),
            timeout=15, message="agent booted")
        agent = agents[0]
        # Fresh registration, first heartbeat not yet landed: alive.
        store.upsert_entity(names.TABLE_NODES, "gracepool", "booting",
                            {"state": "creating",
                             "registered_at": time.time()})
        assert agent._node_alive("booting")
        # Registration older than the staleness window: dead.
        store.upsert_entity(names.TABLE_NODES, "gracepool", "stale",
                            {"state": "creating",
                             "registered_at": time.time() - 60.0})
        assert not agent._node_alive("stale")
        # Legacy row with neither heartbeat nor registration: dead
        # (the pre-grace behavior, unchanged).
        store.upsert_entity(names.TABLE_NODES, "gracepool", "legacy",
                            {"state": "running"})
        assert not agent._node_alive("legacy")
        # A fresh heartbeat always wins.
        store.upsert_entity(names.TABLE_NODES, "gracepool", "alive",
                            {"state": "running",
                             "heartbeat_at": time.time()})
        assert agent._node_alive("alive")
    finally:
        substrate.stop_all()


def test_orphaned_gang_janitor_sweeps_leaked_rows():
    """A gang cleanup cut short mid-flight (store fault between a
    state transition and its row clear, or a claim whose second
    insert failed) leaves rendezvous rows nothing would ever retire.
    The heartbeat janitor sweeps any partition whose task is
    terminal, gone, or past that attempt — and keeps the live
    attempt's rows."""
    store, substrate, pool = _make_pool("janitor")
    tpk = names.task_pk("janitor", "jj")
    store.insert_entity(names.TABLE_JOBS, "janitor", "jj",
                        {"state": "active", "spec": {}})
    # Terminal task with a leaked attempt-0 claim marker.
    store.insert_entity(names.TABLE_TASKS, tpk, "gdone",
                        {"state": "completed", "retries": 0,
                         "spec": {}})
    done_pk = names.gang_pk("janitor", "jj", "gdone")
    store.insert_entity(names.TABLE_GANGS, done_pk, "node$n0",
                        {"instance": 0})
    # Task row gone entirely (job deleted mid-fault).
    ghost_pk = names.gang_pk("janitor", "jj", "ghost")
    store.insert_entity(names.TABLE_GANGS, ghost_pk, "i0",
                        {"state": "joined"})
    # Live task on attempt 2: its stale attempt-0 partition is
    # garbage, its current attempt-2 partition is not.
    store.insert_entity(names.TABLE_TASKS, tpk, "glive",
                        {"state": "running", "retries": 2,
                         "spec": {}})
    stale_pk = names.gang_pk("janitor", "jj", "glive", attempt=0)
    live_pk = names.gang_pk("janitor", "jj", "glive", attempt=2)
    store.insert_entity(names.TABLE_GANGS, stale_pk, "node$n1",
                        {"instance": 0})
    store.insert_entity(names.TABLE_GANGS, live_pk, "i0",
                        {"state": "joined"})
    try:
        # The sweep is leader-gated (lowest-indexed live node).
        agent = next(a for a in
                     substrate._agents["janitor"].values()
                     if a.identity.node_index == 0)
        agent._last_gang_sweep -= agent.gang_sweep_interval + 1
        agent._sweep_orphaned_gangs()
        for pk in (done_pk, ghost_pk, stale_pk):
            assert not list(store.query_entities(
                names.TABLE_GANGS, partition_key=pk)), pk
        assert list(store.query_entities(
            names.TABLE_GANGS, partition_key=live_pk))
    finally:
        substrate.stop_all()


# ----------------------------- full drill ------------------------------

def test_chaos_drill_acceptance_kinds():
    """The acceptance drill: a seeded schedule injecting {wedge,
    mid-run kill, node preemption, heartbeat blackout} over a fakepod
    pool — every injection actually lands, every task ends completed
    exactly once, no orphaned coordination state, and the goodput
    partition stays exact."""
    from batch_shipyard_tpu.chaos.drill import run_drill
    kinds = ("task_wedge", "task_kill", "node_preempt",
             "heartbeat_blackout")
    report = run_drill(seed=5, kinds=kinds, wait_timeout=90.0)
    assert report["invariants"]["ok"]
    # 16 regular tasks + the always-included gang task (which makes
    # the orphaned-gang-rows check below non-vacuous).
    assert report["invariants"]["tasks"] == {"completed": 17}
    assert report["invariants"]["orphaned_gang_rows"] == 0
    assert report["invariants"]["queue_depth"] == 0
    # Every fault kind landed (a drill whose kills miss their victims
    # proves nothing about the kill paths)...
    applied = {a["kind"] for a in report["applied"]
               if a.get("applied")}
    assert applied == set(kinds), report["applied"]
    # ...and healing actually happened: the wedge + kill forced
    # retries, and the supervisor's backoff wait is priced.
    assert report["invariants"]["retries"] >= 1
    assert report["invariants"]["backoff_seconds"] > 0.0
    # The same seed plans the same schedule (CLI `chaos plan`).
    assert (ChaosPlan.generate(5, num_nodes=4, kinds=kinds)
            .fingerprint() == report["fingerprint"])


def test_chaos_drill_store_faults_survived():
    """Store-fault drill: injected latency + an error burst on state
    store ops are absorbed by the agent loops (requeue, retry next
    tick) — no task is lost and the partition stays exact."""
    from batch_shipyard_tpu.chaos.drill import run_drill
    report = run_drill(
        seed=11, tasks=8, duration=3.0, task_sleep=0.5,
        kinds=("store_delay", "store_error"),
        injections_per_kind=2, wait_timeout=60.0)
    assert report["invariants"]["ok"]
    assert report["invariants"]["tasks"] == {"completed": 9}
    applied = {a["kind"] for a in report["applied"]
               if a.get("applied")}
    assert applied == {"store_delay", "store_error"}

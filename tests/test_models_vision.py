"""ViT and DiT model-family tests: shapes, loss descent through the
train harnesses on the virtual 8-device mesh, sampler determinism."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import diffusion as dif_mod
from batch_shipyard_tpu.models import vit as vit_mod
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod

TINY_VIT = vit_mod.ViTConfig(
    image_size=32, patch_size=8, num_classes=10, d_model=64,
    n_layers=2, n_heads=2, d_ff=128, dtype=jnp.float32)

TINY_DIT = dif_mod.DiTConfig(
    image_size=16, patch_size=4, d_model=64, n_layers=2, n_heads=2,
    d_ff=128, timesteps=100, dtype=jnp.float32)


@pytest.mark.slow
def test_vit_forward_shape_and_grad():
    model = vit_mod.ViT(TINY_VIT)
    images = jnp.ones((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), images)["params"]
    logits = model.apply({"params": params}, images)
    assert logits.shape == (2, 10)
    # sincos positions: no position parameter in the tree
    assert "pos_embed" not in params

    def loss(p):
        return vit_mod.cross_entropy_loss(
            model.apply({"params": p}, images),
            jnp.asarray([1, 2], jnp.int32))

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(leaf)) for leaf in leaves)


@pytest.mark.slow
def test_vit_train_loss_decreases():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    harness = train_mod.build_vit_train(
        mesh, TINY_VIT, batch_size=16, learning_rate=1e-3)
    rng = np.random.RandomState(0)
    batch = {
        "images": jnp.asarray(rng.randn(16, 32, 32, 3), jnp.float32),
        "labels": jnp.asarray(rng.randint(0, 10, (16,)), jnp.int32),
    }
    params, opt_state = harness.params, harness.opt_state
    losses = []
    for _ in range(8):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_dit_forward_shape_identity_at_init():
    """adaLN-Zero: with zero-initialized gates and head, the initial
    prediction is exactly zero (every block starts as identity)."""
    model = dif_mod.DiT(TINY_DIT)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    t = jnp.asarray([0, 50], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t, None)["params"]
    pred = model.apply({"params": params}, x, t, None)
    assert pred.shape == (2, 16, 16, 3)
    np.testing.assert_allclose(np.asarray(pred), 0.0, atol=1e-6)


def test_dit_class_conditional_requires_labels():
    cfg = dif_mod.DiTConfig(
        image_size=16, patch_size=4, d_model=64, n_layers=1,
        n_heads=2, d_ff=128, num_classes=10, dtype=jnp.float32)
    model = dif_mod.DiT(cfg)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    t = jnp.zeros((2,), jnp.int32)
    labels = jnp.asarray([3, 7], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t, labels)["params"]
    out = model.apply({"params": params}, x, t, labels)
    assert out.shape == x.shape
    try:
        model.apply({"params": params}, x, t, None)
        raise AssertionError("expected ValueError without labels")
    except ValueError:
        pass


@pytest.mark.slow
def test_diffusion_train_loss_decreases():
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    harness = train_mod.build_diffusion_train(
        mesh, TINY_DIT, batch_size=16, learning_rate=2e-3)
    rng = np.random.RandomState(1)
    x0 = np.tanh(rng.randn(16, 16, 16, 3)).astype(np.float32)
    batch = {"images": jnp.asarray(x0)}
    params, opt_state = harness.params, harness.opt_state
    losses = []
    for _ in range(10):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  batch)
        losses.append(float(metrics["loss"]))
    # At init the prediction is 0 so the loss is E[noise^2] ~= 1.
    assert 0.5 < losses[0] < 2.0
    assert losses[-1] < losses[0]


def test_ddim_sampler_shape_and_determinism():
    model = dif_mod.DiT(TINY_DIT)
    x = jnp.ones((1, 16, 16, 3), jnp.float32)
    t = jnp.zeros((1,), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), x, t, None)["params"]
    key = jax.random.PRNGKey(42)
    a = dif_mod.ddim_sample(model, params, key, num_images=2,
                            num_steps=4)
    b = dif_mod.ddim_sample(model, params, key, num_images=2,
                            num_steps=4)
    assert a.shape == (2, 16, 16, 3)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert np.all(np.isfinite(np.asarray(a)))

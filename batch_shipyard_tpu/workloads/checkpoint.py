"""Training checkpoint/resume via Orbax, sync and zero-stall async.

Reference context (SURVEY.md section 5.4): the reference has no
application checkpointing (it is an orchestrator); for the TPU build,
app-level checkpointing is a workload concern — this module gives the
recipe payloads a save/restore surface over Orbax so preempted or
migrated jobs resume instead of restarting. Orchestrator-level
suspend/resume and job migration live in pool/jobs managers.

Checkpoints go to a local path or, in a pool, typically the job's
shared directory (SHIPYARD_JOB_SHARED_DIR) or a gcsfuse mount so every
worker sees them.

Atomic commit protocol: a save writes into a hidden staging directory
(``.tmp_step_NNNNNNNN``), stamps a COMMITTED marker, then renames into
place — so a crash mid-save can never leave a torn ``step_NNNNNNNN``
that ``latest_step``/``restore`` would pick up and resume a corrupt
state from. ``latest_step`` only considers dirs carrying the marker,
which also skips torn dirs written by pre-marker versions. This is
what makes the goodput "lost-step rework" number honest: resume
always lands on the last DURABLE step, and the replayed step window
after a preemption is exactly the badput the accounting charges.

Two save paths share that protocol:

  * ``save()`` — blocking: the caller pays device→host transfer +
    Orbax serialize + fsync + rename before the next step runs.
  * ``AsyncCheckpointManager`` — zero-stall (arxiv 2502.06982's
    checkpoint-overhead prescription): the step boundary only pays a
    device→host snapshot into a fresh host buffer (double-buffered —
    the in-flight save keeps its own copy while the next one
    snapshots); a background writer thread runs staging→barrier→
    commit and keep-last-N retention GC. The queue is bounded at
    depth 1: a new save waits for the in-flight persist, so host
    memory never holds more than two snapshots. Background failures
    re-raise at the next enqueue/drain — silent checkpoint loss is
    forbidden.

Goodput attribution (docs/28-checkpointing.md): the blocking portion
of either path records PROGRAM_CHECKPOINT_SAVE (checkpoint badput);
the async manager's overlapped persist records
PROGRAM_CHECKPOINT_ASYNC, which the accounting sweep scores as
productive-overlapped when live step windows cover it — the waterfall
shows the persist without charging it as a stall.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
from typing import Any, Optional

from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.agent import progress
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

COMMIT_MARKER = "COMMITTED"
# Sidecar recording the mesh a checkpoint was SAVED on (axis sizes +
# device count), written next to the COMMITTED marker. restore()
# compares it against the restore templates' mesh: a mismatch routes
# through the reshard-on-restore path (parallel/sharding.py) instead
# of handing Orbax shardings the checkpoint never had. Absent on
# legacy dirs and host-snapshot saves — those restore strictly.
MESH_MARKER = "MESH"


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        f"step_{step:08d}")


def _staging_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        f".tmp_step_{step:08d}")


def _marker_path(checkpoint_dir: str, step: int) -> str:
    # Sibling file, not inside the step dir: Orbax owns the dir's
    # contents and must never see a foreign entry on restore.
    return _step_path(checkpoint_dir, step) + "." + COMMIT_MARKER


def is_committed(checkpoint_dir: str, step: int) -> bool:
    return os.path.exists(_marker_path(checkpoint_dir, step))


def _mesh_meta_path(checkpoint_dir: str, step: int) -> str:
    return _step_path(checkpoint_dir, step) + "." + MESH_MARKER


def mesh_meta_of(tree: Any) -> Optional[dict]:
    """{"mesh_shape": {axis: size}, "mesh_devices": N} from the first
    mesh-sharded leaf of a pytree, or None (host arrays / no mesh)."""
    import jax
    for leaf in jax.tree_util.tree_leaves(tree):
        mesh = getattr(getattr(leaf, "sharding", None), "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape:
            try:
                return {"mesh_shape": {str(k): int(v)
                                       for k, v in dict(shape).items()},
                        "mesh_devices": int(
                            max(1, len(mesh.devices.reshape(-1))))}
            except Exception:  # noqa: BLE001 - metadata only
                return None
    return None


def saved_mesh_meta(checkpoint_dir: str,
                    step: int) -> Optional[dict]:
    """The mesh a committed step was saved on (sidecar), or None for
    legacy/host-snapshot saves."""
    try:
        with open(_mesh_meta_path(checkpoint_dir, step),
                  encoding="utf-8") as fh:
            meta = json.load(fh)
    except (OSError, ValueError):
        return None
    return meta if isinstance(meta, dict) else None


def _commit_barrier(step: int) -> None:
    """Multi-host commit barrier: every host's shards must be durable
    before process 0 stamps the marker — otherwise a crash between one
    host's write and another's would commit a checkpoint that is torn
    ACROSS hosts (each host's staging dir looks whole locally)."""
    import jax
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"checkpoint_commit_{step}")


def _persist_state(checkpoint_dir: str, step: int,
                   state: dict,
                   mesh_meta: Optional[dict] = None) -> str:
    """The durable half of a save: staging dir → Orbax write →
    multi-host barrier → marker commit. Shared by the blocking
    ``save()`` and the async writer thread. ``mesh_meta`` (the mesh
    the state was sharded on at snapshot time) lands in the .MESH
    sidecar so restore can detect a resize."""
    import jax
    path = _step_path(checkpoint_dir, step)
    staging = _staging_path(checkpoint_dir, step)
    if jax.process_index() == 0:
        os.makedirs(checkpoint_dir, exist_ok=True)
        # A stale staging dir is a previous torn save: discard.
        shutil.rmtree(staging, ignore_errors=True)
    _checkpointer().save(staging, state, force=True)
    _commit_barrier(step)
    if jax.process_index() == 0:
        # Commit order: replace the step dir, THEN stamp the
        # marker (atomically, tmp + rename) — a crash at any
        # point leaves either a previously committed step or an
        # unmarked (ignored) dir, never a torn pickup. A marker
        # orphaned by a crash mid-overwrite is harmless:
        # latest_step only considers EXISTING step dirs.
        marker = _marker_path(checkpoint_dir, step)
        shutil.rmtree(path, ignore_errors=True)
        os.replace(staging, path)
        if mesh_meta is None:
            mesh_meta = mesh_meta_of(state.get("params"))
        if mesh_meta:
            # Sidecar BEFORE the marker: once committed, the mesh
            # record is already durable (a crash between the two
            # leaves an unmarked, ignored step).
            meta_tmp = _mesh_meta_path(checkpoint_dir, step) + ".tmp"
            with open(meta_tmp, "w", encoding="utf-8") as fh:
                fh.write(json.dumps(mesh_meta))
            os.replace(meta_tmp,
                       _mesh_meta_path(checkpoint_dir, step))
        marker_tmp = marker + ".tmp"
        with open(marker_tmp, "w", encoding="utf-8") as fh:
            fh.write(util.datetime_utcnow_iso())
        os.replace(marker_tmp, marker)
    logger.info("checkpoint saved: %s", path)
    return path


def save(checkpoint_dir: str, step: int, params: Any,
         opt_state: Any, *, force: bool = False) -> Optional[str]:
    """Write checkpoint step N atomically (blocking); returns its
    path, or None when the save was skipped because step N is not
    newer than the latest committed step (a resumed job re-saving its
    restore point would burn a full save for nothing). ``force``
    overrides the guard."""
    latest = latest_step(checkpoint_dir)
    if not force and latest is not None and step <= latest:
        logger.info(
            "skipping checkpoint save of step %d: step %d is already "
            "committed in %s", step, latest, checkpoint_dir)
        return None
    state = {"params": params, "opt_state": opt_state,
             "step": step}
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_SAVE, step=step), \
            trace_spans.phase(trace_spans.SPAN_CKPT_PERSIST,
                              step=step, overlapped=False):
        path = _persist_state(checkpoint_dir, step, state)
    return path


def _committed_steps(checkpoint_dir: str) -> list[int]:
    """Sorted step numbers carrying the COMMITTED marker (strict:
    legacy pre-marker dirs are NOT included — retention must never
    delete what it cannot prove durable)."""
    if not os.path.isdir(checkpoint_dir):
        return []
    steps = []
    for name in os.listdir(checkpoint_dir):
        if not (name.startswith("step_")
                and name.endswith("." + COMMIT_MARKER)):
            continue
        try:
            step = int(name.split("_", 1)[1].split(".", 1)[0])
        except ValueError:
            continue
        if os.path.isdir(_step_path(checkpoint_dir, step)):
            steps.append(step)
    return sorted(steps)


def retention_gc(checkpoint_dir: str, keep_last: int) -> list[int]:
    """Delete all but the newest ``keep_last`` COMMITTED checkpoints;
    returns the removed step numbers. Invariants: the newest committed
    step and any in-flight staging dir (``.tmp_step_*``) are never
    touched, and legacy unmarked dirs are left alone (they cannot be
    proven durable, so they cannot be proven safe to drop either).
    Marker removed FIRST: a crash mid-GC leaves an unmarked (ignored)
    dir, never a marked dir with missing contents."""
    import jax
    if keep_last < 1 or jax.process_index() != 0:
        return []
    victims = _committed_steps(checkpoint_dir)[:-keep_last]
    for step in victims:
        try:
            os.remove(_marker_path(checkpoint_dir, step))
        except OSError:
            pass
        try:
            os.remove(_mesh_meta_path(checkpoint_dir, step))
        except OSError:
            pass
        shutil.rmtree(_step_path(checkpoint_dir, step),
                      ignore_errors=True)
        logger.info("checkpoint retention: removed step %d from %s",
                    step, checkpoint_dir)
    return victims


def latest_step(checkpoint_dir: str) -> Optional[int]:
    """Highest COMMITTED step, skipping torn/uncommitted dirs.

    Legacy compatibility: a directory written ENTIRELY by pre-marker
    versions (no .COMMITTED files at all) keeps the old accept-all
    behavior — upgrading must not silently discard a fleet's existing
    resume points. As soon as one marker exists, enforcement is
    strict: unmarked step dirs are torn saves."""
    if not os.path.isdir(checkpoint_dir):
        return None
    entries = os.listdir(checkpoint_dir)
    any_marker = any(name.endswith("." + COMMIT_MARKER)
                     for name in entries)
    steps = []
    for name in entries:
        if name.startswith("step_") and \
                not name.endswith("." + COMMIT_MARKER):
            try:
                step = int(name.split("_", 1)[1])
            except ValueError:
                continue
            if any_marker and not is_committed(checkpoint_dir, step):
                logger.warning(
                    "skipping uncommitted checkpoint %s (torn save)",
                    os.path.join(checkpoint_dir, name))
                continue
            steps.append(step)
    return max(steps) if steps else None


def restore_params(checkpoint_dir: str) -> Optional[tuple]:
    """Restore only the params of the latest checkpoint (serving:
    the optimizer state is irrelevant and its template unavailable).
    Returns (params, step) or None. Arrays land unsharded on the
    default device — single-host serving replicas."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    with goodput_events.phase(
            goodput_events.PROGRAM_CHECKPOINT_RESTORE, step=step), \
            trace_spans.phase(trace_spans.SPAN_CKPT_RESTORE,
                              step=step):
        restored = _checkpointer().restore(path)
    logger.info("checkpoint params restored: %s", path)
    return restored["params"], restored.get("step", step)


def restore(checkpoint_dir: str, params_template: Any,
            opt_state_template: Any,
            allow_reshard: bool = True) -> Optional[tuple]:
    """Restore the latest committed checkpoint matching the given
    pytree structure (shardings preserved from the templates); returns
    (params, opt_state, step) or None when no checkpoint exists.

    Elastic resume: when the checkpoint's .MESH sidecar records a
    DIFFERENT mesh than the templates (a gang that re-formed at a new
    size), the restore routes through the reshard-on-restore path
    (parallel/sharding.py) — full arrays are read host-side and
    re-laid-out onto the templates' shardings. A strict restore that
    fails for any reason falls back the same way (legacy dirs with no
    sidecar included), unless ``allow_reshard=False``."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    if allow_reshard:
        saved_mesh = (saved_mesh_meta(checkpoint_dir, step)
                      or {}).get("mesh_shape")
        current_mesh = (mesh_meta_of(params_template)
                        or {}).get("mesh_shape")
        if saved_mesh and current_mesh and saved_mesh != current_mesh:
            from batch_shipyard_tpu.parallel import (
                sharding as shard_rules)
            logger.warning(
                "checkpoint step %d was saved on mesh %s; "
                "re-sharding onto %s", step, saved_mesh,
                current_mesh)
            return shard_rules.reshard_on_restore(
                checkpoint_dir, params_template, opt_state_template)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": step}
    import orbax.checkpoint as ocp
    try:
        with goodput_events.phase(
                goodput_events.PROGRAM_CHECKPOINT_RESTORE,
                step=step), \
                trace_spans.phase(trace_spans.SPAN_CKPT_RESTORE,
                                  step=step):
            restored = _checkpointer().restore(
                path, item=template,
                restore_args=(
                    ocp.checkpoint_utils.construct_restore_args(
                        template)))
    except Exception as exc:  # noqa: BLE001 - mesh-mismatch shapes
        # vary by orbax version; the reshard path is the one recovery
        # that works for all of them
        if not allow_reshard:
            raise
        from batch_shipyard_tpu.parallel import (
            sharding as shard_rules)
        logger.warning(
            "strict restore of step %d failed (%s); retrying via "
            "the reshard-on-restore path", step, exc)
        return shard_rules.reshard_on_restore(
            checkpoint_dir, params_template, opt_state_template)
    logger.info("checkpoint restored: %s", path)
    return restored["params"], restored["opt_state"], restored["step"]


# --------------------- zero-stall async pipeline -----------------------

class AsyncCheckpointManager:
    """Double-buffered zero-stall save pipeline.

    ``save()`` blocks only for the device→host snapshot (plus any wait
    for a still-in-flight previous persist — the depth-1 queue bound);
    a background writer thread then runs the identical
    staging→barrier→commit protocol and keep-last-N retention GC.
    The blocking portion records PROGRAM_CHECKPOINT_SAVE; the
    overlapped persist records PROGRAM_CHECKPOINT_ASYNC.

    Error contract: a failed background persist is re-raised at the
    next ``save()``/``wait_until_finished()``/``close()`` — a training
    loop can never silently outrun a checkpoint pipeline that stopped
    writing. After the raise the failed step is forgotten (the guard
    falls back to the last COMMITTED step) so the caller may retry it.
    """

    def __init__(self, checkpoint_dir: str,
                 keep_last: int = 0) -> None:
        self.checkpoint_dir = os.path.abspath(checkpoint_dir)
        self.keep_last = int(keep_last or 0)
        self._queue: queue.Queue = queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._last_enqueued: Optional[int] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._writer_loop, name="ckpt-async-writer",
            daemon=True)
        self._thread.start()

    # -- writer thread ---------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                step, state, mesh_meta = item
                try:
                    with goodput_events.phase(
                            goodput_events.PROGRAM_CHECKPOINT_ASYNC,
                            step=step), \
                            trace_spans.phase(
                                trace_spans.SPAN_CKPT_PERSIST,
                                step=step, overlapped=True):
                        _persist_state(self.checkpoint_dir, step,
                                       state, mesh_meta=mesh_meta)
                    if self.keep_last:
                        retention_gc(self.checkpoint_dir,
                                     self.keep_last)
                except BaseException as exc:  # noqa: BLE001 - must
                    # propagate to the trainer, never die silently
                    logger.error("async checkpoint save of step %d "
                                 "failed: %s", step, exc)
                    self._error = exc
            finally:
                self._queue.task_done()

    # -- caller side -----------------------------------------------

    def _raise_pending_error(self) -> None:
        if self._error is not None:
            exc, self._error = self._error, None
            # The failed step never committed: let the guard fall
            # back to disk truth so a retry of that step is allowed.
            self._last_enqueued = latest_step(self.checkpoint_dir)
            raise exc

    def _should_skip(self, step: int) -> bool:
        # Once a step has been enqueued it supersedes disk state (the
        # writer only ever commits enqueued steps), so the hot path
        # skips the directory scan — latest_step() on a gcsfuse mount
        # is exactly the stall class this pipeline removes. Disk is
        # consulted only before the first enqueue (and after an error,
        # which resets _last_enqueued from disk truth).
        if self._last_enqueued is not None:
            return step <= self._last_enqueued
        high_water = latest_step(self.checkpoint_dir)
        return high_water is not None and step <= high_water

    def save(self, step: int, params: Any,
             opt_state: Any) -> Optional[str]:
        """Snapshot + enqueue. Blocks O(device→host transfer), not
        O(fsync). Returns the (eventual) step path, or None when the
        step is not newer than the latest committed/enqueued step."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointManager is closed")
        self._raise_pending_error()
        step = int(step)
        if self._should_skip(step):
            logger.info(
                "skipping async checkpoint save of step %d: not newer "
                "than the latest committed/in-flight step", step)
            return None
        import jax
        if jax.process_count() > 1:
            # Multi-host: the double-buffered host snapshot would
            # fetch non-addressable shards (device_get raises), every
            # process would race Orbax's per-host shard layout in the
            # shared staging dir, and the writer thread's commit
            # barrier would interleave with the step loop's
            # collectives. Until a per-host async writer lands,
            # degrade to the blocking protocol — correctness over
            # overlap.
            logger.warning(
                "async checkpointing is single-host only; falling "
                "back to the blocking save for step %d", step)
            path = save(self.checkpoint_dir, step, params, opt_state,
                        force=True)
            if self.keep_last:
                retention_gc(self.checkpoint_dir, self.keep_last)
            self._last_enqueued = step
            return path
        with goodput_events.phase(
                goodput_events.PROGRAM_CHECKPOINT_SAVE, step=step,
                mode="snapshot"), \
                trace_spans.phase(trace_spans.SPAN_CKPT_SNAPSHOT,
                                  step=step):
            # Snapshot FIRST (the second buffer), so the in-flight
            # persist keeps overlapping with the transfer; then wait
            # out the depth-1 bound. Mesh metadata is read off the
            # live (still-sharded) params — the host snapshot has no
            # shardings left to record.
            mesh_meta = mesh_meta_of(params)
            state = jax.device_get(
                {"params": params, "opt_state": opt_state})
            state["step"] = step
            self._queue.join()
            # A persist that failed while we waited must surface
            # before this step is enqueued on top of the hole.
            self._raise_pending_error()
            self._queue.put((step, state, mesh_meta))
            self._last_enqueued = step
        return _step_path(self.checkpoint_dir, step)

    def wait_until_finished(self) -> None:
        """Drain the in-flight persist; re-raises its failure. Call
        at loop exit and before any restore."""
        self._queue.join()
        self._raise_pending_error()

    def restore(self, params_template: Any,
                opt_state_template: Any) -> Optional[tuple]:
        """Drain, then restore the latest committed checkpoint (an
        in-flight save must become pickable before we decide where to
        resume)."""
        self.wait_until_finished()
        return restore(self.checkpoint_dir, params_template,
                       opt_state_template)

    def close(self) -> None:
        """Drain, stop the writer thread, re-raise any failure."""
        if self._closed:
            return
        self._closed = True
        self._queue.join()
        self._queue.put(None)
        self._thread.join(timeout=60.0)
        self._raise_pending_error()

    def __enter__(self) -> "AsyncCheckpointManager":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()


# ----------------------- shared train-loop driver ----------------------

def add_checkpoint_args(parser) -> None:
    """The shared checkpoint flag surface of every train_* workload."""
    group = parser.add_argument_group("checkpointing")
    group.add_argument("--checkpoint-dir", default=None,
                       help="Orbax checkpoint dir (use the job "
                            "shared dir or a gcsfuse mount on pools)")
    group.add_argument("--checkpoint-every", type=int, default=0,
                       help="Save every N steps (0 = only at end)")
    group.add_argument("--async-checkpoint", action="store_true",
                       help="zero-stall saves: snapshot on the step "
                            "boundary, persist in a background "
                            "writer thread")
    group.add_argument("--keep-last", type=int, default=0,
                       help="retention: keep only the newest N "
                            "committed checkpoints (0 = keep all)")


class TrainCheckpointer:
    """Checkpoint driver for train loops: restore-at-start, cadenced
    saves, deduplicated final save, drain-at-exit. Wraps either the
    blocking ``save()`` path or an AsyncCheckpointManager, so the
    four train_* workloads share one integration instead of four
    hand-rolled (and historically divergent) ones."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 every: int = 0, use_async: bool = False,
                 keep_last: int = 0) -> None:
        self.checkpoint_dir = checkpoint_dir
        self.every = int(every or 0)
        self.keep_last = int(keep_last or 0)
        self.manager: Optional[AsyncCheckpointManager] = None
        if checkpoint_dir and use_async:
            self.manager = AsyncCheckpointManager(
                checkpoint_dir, keep_last=self.keep_last)
        # Cooperative preemption: the agent drops a request file
        # ($SHIPYARD_PREEMPT_REQUEST_FILE); maybe_preempt polls it at
        # step boundaries (one os.stat while disarmed — the
        # StepProfiler cost model). No-op outside pools.
        self._preempt = preemption.PreemptWatcher()

    @classmethod
    def from_args(cls, args) -> "TrainCheckpointer":
        return cls(checkpoint_dir=args.checkpoint_dir,
                   every=args.checkpoint_every,
                   use_async=args.async_checkpoint,
                   keep_last=args.keep_last)

    @property
    def enabled(self) -> bool:
        return bool(self.checkpoint_dir)

    def due(self, completed_steps: int) -> bool:
        """True when the loop should save at this step boundary."""
        return bool(self.enabled and self.every
                    and completed_steps % self.every == 0)

    def restore(self, params: Any, opt_state: Any) -> tuple:
        """(params, opt_state, start_step); passthrough with
        start_step 0 when disabled or nothing is committed."""
        if not self.enabled:
            return params, opt_state, 0
        if self.manager is not None:
            restored = self.manager.restore(params, opt_state)
        else:
            restored = restore(self.checkpoint_dir, params, opt_state)
        if restored is None:
            return params, opt_state, 0
        return restored

    def _save(self, step: int, params: Any, opt_state: Any) -> None:
        if self.manager is not None:
            self.manager.save(step, params, opt_state)
        else:
            saved = save(self.checkpoint_dir, step, params, opt_state)
            if saved is not None and self.keep_last:
                retention_gc(self.checkpoint_dir, self.keep_last)
        # Scheduling hint: steps-since-last-commit is the dominant
        # term in victim-cost pricing (sched/policy.py victim_cost) —
        # advertising the commit makes this task CHEAP to preempt
        # right after a save and progressively dearer as unsaved work
        # accumulates.
        progress.record_sched_hints(ckpt_step=step)

    def step_save(self, completed_steps: int, params: Any,
                  opt_state: Any) -> bool:
        """Cadenced save at a step boundary; no-op off cadence."""
        if not self.due(completed_steps):
            return False
        self._save(completed_steps, params, opt_state)
        return True

    def maybe_preempt(self, completed_steps: int, params: Any,
                      opt_state: Any) -> bool:
        """Cooperative drain: True when a preempt request is pending
        — a COMMITTED checkpoint of this step boundary was forced
        (async persist drained, so the commit is durable BEFORE the
        process exits), and the caller must flush its step window and
        exit ``preemption.EXIT_PREEMPTED``. The rerun resumes here:
        zero lost steps beyond this barrier."""
        request = self._preempt.poll()
        if request is None:
            return False
        if self.enabled:
            if self.manager is not None:
                self.manager.save(completed_steps, params, opt_state)
                self.manager.wait_until_finished()
            else:
                save(self.checkpoint_dir, completed_steps, params,
                     opt_state)
        logger.warning(
            "preempt drain complete at step %d%s; exiting with the "
            "preempted status", completed_steps,
            "" if self.enabled else " (no checkpoint dir configured)")
        return True

    def finalize(self, final_step: int, params: Any,
                 opt_state: Any) -> None:
        """Exit save + drain. The save guard skips the write when the
        loop's cadenced save already committed (or enqueued) this very
        step — the historical duplicate final save paid a full persist
        for a byte-identical checkpoint."""
        if not self.enabled:
            return
        try:
            self._save(final_step, params, opt_state)
        finally:
            if self.manager is not None:
                self.manager.close()

"""ViT image-classification training payload (the reference's
Caffe/MXNet/CNTK image-classification recipes' workload analog,
/root/reference/recipes/Caffe-GPU/README.md — TPU-native model instead
of a framework container).

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.train_vit \
        --batch-per-device 128 --steps 50
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu import compilecache
from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.models import vit as vit_mod
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod
from batch_shipyard_tpu.workloads import checkpoint
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-per-device", type=int, default=128)
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--patch-size", type=int, default=16)
    parser.add_argument("--d-model", type=int, default=768)
    parser.add_argument("--layers", type=int, default=12)
    parser.add_argument("--heads", type=int, default=12)
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--warmup", type=int, default=3)
    checkpoint.add_checkpoint_args(parser)
    compilecache.add_compile_cache_args(parser)
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    batch_size = args.batch_per_device * n_dev
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    config = vit_mod.ViTConfig(
        image_size=args.image_size, patch_size=args.patch_size,
        num_classes=args.num_classes, d_model=args.d_model,
        n_layers=args.layers, n_heads=args.heads,
        d_ff=4 * args.d_model, dtype=jnp.bfloat16)
    compilecache.enable_from_args(
        args, mesh_shape=dict(mesh.shape),
        model_digest=compilecache.config_digest(config))
    harness = train_mod.build_vit_train(mesh, config,
                                        batch_size=batch_size)
    join_aot = (compilecache.aot.precompile_async(harness)
                if args.aot_precompile else None)
    from batch_shipyard_tpu.data import loader

    rng = np.random.RandomState(jax.process_index())
    local_batch = batch_size // jax.process_count()
    synthetic = loader.place_global({
        "images": np.asarray(
            rng.randn(local_batch, args.image_size, args.image_size,
                      3), np.float32),
        "labels": np.asarray(
            rng.randint(0, args.num_classes, (local_batch,)),
            np.int32),
    }, harness.batch_sharding)
    params, opt_state = harness.params, harness.opt_state
    ckpt = checkpoint.TrainCheckpointer.from_args(args)
    params, opt_state, start_step = ckpt.restore(params, opt_state)
    if start_step:
        distributed.log(ctx, f"resumed from step {start_step}")
    if join_aot is not None:
        join_aot()
    for _ in range(args.warmup):
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  synthetic)
        float(metrics["loss"])  # hard sync
    # On-demand profiling: `shipyard jobs profile` (trace/profiling).
    from batch_shipyard_tpu.trace.profiling import StepProfiler
    profiler = StepProfiler()
    start = time.perf_counter()
    for step_num in range(start_step, start_step + args.steps):
        profiler.tick(step_num)
        params, opt_state, metrics = harness.step(params, opt_state,
                                                  synthetic)
        # Cooperative preemption: force-commit this boundary and exit
        # with the distinct preempted status (requeued at full
        # budget; the rerun resumes here).
        if ckpt.maybe_preempt(step_num + 1, params, opt_state):
            profiler.close()
            return preemption.EXIT_PREEMPTED
        ckpt.step_save(step_num + 1, params, opt_state)
    loss = float(metrics["loss"])
    profiler.close()
    elapsed = time.perf_counter() - start
    ckpt.finalize(start_step + args.steps, params, opt_state)
    images_per_sec = batch_size * args.steps / elapsed
    distributed.log(ctx, (
        f"vit: mesh={dict(mesh.shape)} {images_per_sec:.1f} img/s "
        f"total, {images_per_sec / n_dev:.1f} img/s/chip, "
        f"loss={loss:.4f}"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Distributed train-step builders: mesh + model -> jitted SPMD step.

The compute-path capstone: these are what the -TPU recipes and the
benchmark run. Everything is jit-compiled global-view SPMD — shardings
annotated via in_shardings/with_sharding_constraint, collectives
inserted by XLA, ring attention dropped in through the model's
attention_fn when the mesh has an sp axis.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from batch_shipyard_tpu.agent import progress as progress_mod
from batch_shipyard_tpu.compilecache import manager as cc_manager
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.models import resnet as resnet_mod
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.ops import ring_attention as ring
from batch_shipyard_tpu.parallel import sharding as shard_rules


@dataclasses.dataclass
class TrainHarness:
    """A compiled training setup: params/opt state live sharded on the
    mesh; step(params, opt_state, batch) -> (params, opt_state,
    metrics)."""

    mesh: Mesh
    params: Any
    opt_state: Any
    step: Callable
    batch_sharding: Any
    # AOT warm start (compilecache/aot.py): lower+compile the step
    # against abstract batch shapes and swap the executable into the
    # step hot path, so the first real step runs the same compiled
    # program as the steady state — no cold-compile spike. None for
    # builders without an AOT path (the pipeline schedules).
    precompile: Optional[Callable[[], None]] = None


def _aot_step(compiled: dict, step: Callable, *args):
    """Dispatch through the AOT executable when one is installed.
    Signature/layout mismatches (an abstract-shape guess that doesn't
    match the real batch) raise at call validation, BEFORE any donated
    buffer is consumed — drop the executable and fall back to the jit
    path, which compiles for the true signature."""
    fn = compiled.get("step")
    if fn is not None:
        try:
            return fn(*args)
        except (TypeError, ValueError):
            compiled.pop("step", None)
    return step(*args)


def make_transformer_config(mesh: Optional[Mesh] = None,
                            **overrides) -> tfm.TransformerConfig:
    """Build a config whose attention_fn matches the mesh: ring
    attention when sp > 1, flash/blockwise otherwise."""
    attention_fn = overrides.pop("attention_fn", None)
    if attention_fn is None and mesh is not None and \
            mesh.shape.get("sp", 1) > 1:
        def attention_fn(q, k, v, causal):
            return ring.ring_attention(q, k, v, mesh, axis_name="sp",
                                       causal=causal)
    return tfm.TransformerConfig(attention_fn=attention_fn, **overrides)


def build_transformer_train(
        mesh: Mesh, config: tfm.TransformerConfig,
        batch_size: int, seq_len: int,
        learning_rate: float = 3e-4,
        seed: int = 0) -> TrainHarness:
    model = tfm.TransformerLM(config)
    optimizer = optax.adamw(learning_rate, weight_decay=0.01)

    tokens_shape = (batch_size, seq_len)
    batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))

    def init_fn(rng):
        tokens = jnp.zeros(tokens_shape, dtype=jnp.int32)
        params = model.init(rng, tokens)["params"]
        return params

    rng = jax.random.PRNGKey(seed)
    abstract = jax.eval_shape(init_fn, rng)
    param_specs = shard_rules.transformer_param_specs(abstract)
    param_shardings = shard_rules.to_shardings(mesh, param_specs)
    # Param/opt-state init is jit-compile time: charge it to the
    # compile badput category (no-op outside a pool task), stamped
    # with the persistent cache's hit/saved detail when enabled.
    with goodput_events.phase(goodput_events.PROGRAM_COMPILE,
                              what="init") as init_attrs, \
            cc_manager.tracked(init_attrs, "transformer_init"):
        # Sharding-invariant init draws (utils/compat): the same seed
        # must produce the same parameters on a dp-only and a tp/sp
        # mesh, or the parallelism configs can never agree.
        from batch_shipyard_tpu.utils import compat
        with compat.threefry_partitionable():
            params = jax.jit(init_fn,
                             out_shardings=param_shardings)(rng)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=None)(params)

    def loss_fn(params, tokens, targets):
        # Chunked tied-embedding loss: the full [B, T, vocab] fp32
        # logits tensor never materializes (see lm_loss_chunked).
        hidden, variables = model.apply(
            {"params": params}, tokens, return_hidden=True,
            mutable=["losses"])
        loss = tfm.lm_loss_chunked(
            hidden, params["embed"]["embedding"], targets)
        # MoE load-balancing auxiliary losses (if any blocks sowed).
        aux_leaves = jax.tree_util.tree_leaves(
            variables.get("losses", {}))
        if aux_leaves:
            loss = loss + config.moe_aux_weight * sum(
                jnp.mean(a) for a in aux_leaves)
        return loss

    # Pin the opt-state shardings SYMMETRICALLY (in == out == the
    # initialized buffers' actual shardings): opt_state is donated,
    # and leaving out_shardings to XLA lets the compiler pick a
    # different layout than the donated input buffer under tp — a
    # runtime aliasing size mismatch, not a resharding. Leaves that
    # initialized off-mesh (optax scalar counts land on one device)
    # are normalized to mesh-replicated and re-placed.
    def _opt_sharding(x):
        if isinstance(x.sharding, NamedSharding) and \
                x.sharding.mesh == mesh:
            return x.sharding
        return NamedSharding(mesh, P())

    opt_shardings = jax.tree_util.tree_map(_opt_sharding, opt_state)
    opt_state = jax.device_put(opt_state, opt_shardings)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=(param_shardings, opt_shardings, batch_sharding,
                      batch_sharding),
        out_shardings=(param_shardings, opt_shardings, None))
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                  targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    compiled: dict = {}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        params, opt_state, metrics = _aot_step(
            compiled, step, params, opt_state, batch["tokens"],
            batch["targets"])
        return params, opt_state, metrics

    def precompile():
        tokens_abs = jax.ShapeDtypeStruct(tokens_shape, jnp.int32,
                                          sharding=batch_sharding)
        compiled["step"] = step.lower(
            params, opt_state, tokens_abs, tokens_abs).compile()

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding,
                        precompile=precompile)


def build_transformer_train_pp(
        mesh: Mesh, config: tfm.TransformerConfig,
        batch_size: int, seq_len: int,
        num_microbatches: int = 4,
        learning_rate: float = 3e-4,
        seed: int = 0) -> TrainHarness:
    """Pipeline-parallel transformer training: blocks are split into
    pp stages (mesh must have a 'pp' axis; n_layers divisible by its
    size), microbatches flow through the GPipe wavefront
    (parallel/pipeline.py), embedding + final norm + chunked loss run
    outside the pipelined middle, and data parallelism rides the
    mesh's 'dp' axis.
    """
    from batch_shipyard_tpu.parallel import pipeline as pipe
    num_stages = mesh.shape["pp"]
    if config.n_layers % num_stages:
        raise ValueError(
            f"n_layers {config.n_layers} not divisible by pp "
            f"{num_stages}")
    layers_per_stage = config.n_layers // num_stages
    block = tfm.Block(config)
    embed = __import__("flax.linen", fromlist=["linen"]).Embed(
        config.vocab_size, config.d_model, dtype=config.dtype,
        param_dtype=config.param_dtype)
    norm = tfm.RMSNorm(dtype=config.dtype)
    positions = jnp.arange(seq_len, dtype=jnp.int32)

    rng = jax.random.PRNGKey(seed)
    rngs = jax.random.split(rng, config.n_layers + 2)
    x0 = jnp.zeros((1, seq_len, config.d_model), config.dtype)
    per_layer = [block.init(rngs[i], x0, positions)["params"]
                 for i in range(config.n_layers)]
    # Leaves become [S, Lp, ...]: stage-major stack of layer stacks.
    per_stage = [
        pipe.stack_stage_params(
            per_layer[s * layers_per_stage:(s + 1) * layers_per_stage])
        for s in range(num_stages)]
    stage_params = pipe.stack_stage_params(per_stage)
    params = {
        "embed": embed.init(rngs[-2],
                            jnp.zeros((1, seq_len), jnp.int32))[
                                "params"],
        "stages": stage_params,
        "final_norm": norm.init(rngs[-1], x0)["params"],
    }
    optimizer = optax.adamw(learning_rate, weight_decay=0.01)

    def stage_fn(stage_p, x):
        # stage_p leaves: [Lp, ...]; scan the stage's layers.
        def layer_step(h, layer_p):
            return block.apply({"params": layer_p}, h, positions), None
        out, _ = jax.lax.scan(layer_step, x, stage_p)
        return out

    batch_sharding = NamedSharding(mesh, P("dp"))
    param_specs = {
        "embed": jax.tree_util.tree_map(lambda _: P(), params["embed"]),
        "stages": jax.tree_util.tree_map(
            lambda p: P("pp", *([None] * (p.ndim - 1))),
            params["stages"]),
        "final_norm": jax.tree_util.tree_map(
            lambda _: P(), params["final_norm"]),
    }
    param_shardings = shard_rules.to_shardings(mesh, param_specs)
    params = jax.device_put(params, param_shardings)
    opt_state = optimizer.init(params)

    def loss_fn(params, tokens, targets):
        h = embed.apply({"params": params["embed"]}, tokens)
        h = pipe.pipeline_apply(
            params["stages"], h, mesh=mesh, stage_fn=stage_fn,
            num_microbatches=num_microbatches, batch_axes=("dp",))
        h = norm.apply({"params": params["final_norm"]}, h)
        return tfm.lm_loss_chunked(
            h, params["embed"]["embedding"], targets)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=(param_shardings, None, batch_sharding,
                      batch_sharding),
        out_shardings=(param_shardings, None, None))
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                  targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        params, opt_state, metrics = step(
            params, opt_state, batch["tokens"], batch["targets"])
        return params, opt_state, metrics

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding)


def build_transformer_train_1f1b(
        mesh: Mesh, config: tfm.TransformerConfig,
        batch_size: int, seq_len: int,
        num_microbatches: int = 8,
        learning_rate: float = 3e-4,
        seed: int = 0) -> TrainHarness:
    """Pipeline-parallel transformer training on the 1F1B schedule
    (parallel/pipeline.pipeline_1f1b_train): same model split as
    build_transformer_train_pp, but the backward interleaves with the
    forward so pipeline memory is bounded by the stage count instead
    of the microbatch count, with stage-granular recompute. The tied
    embedding's gradient combines the token-gather path (via the
    pipeline's dx) and the CE head path (inside last_fn).
    """
    from flax import linen as nn

    from batch_shipyard_tpu.parallel import pipeline as pipe
    num_stages = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    if config.n_layers % num_stages:
        raise ValueError(
            f"n_layers {config.n_layers} not divisible by pp "
            f"{num_stages}")
    if tp > 1 and (config.n_heads % tp or config.d_ff % tp):
        raise ValueError(
            f"n_heads {config.n_heads} and d_ff {config.d_ff} must "
            f"both be divisible by tp {tp}")
    layers_per_stage = config.n_layers // num_stages
    # Params are initialized at GLOBAL shapes; inside the pipeline's
    # shard_map each tp member sees its column/row shard, so the
    # APPLY-side block uses local head/ff counts and owns the Megatron
    # psums (TransformerConfig.tp_axis).
    block = tfm.Block(config)
    apply_block = block
    if tp > 1:
        apply_block = tfm.Block(dataclasses.replace(
            config, n_heads=config.n_heads // tp,
            d_ff=config.d_ff // tp, tp_axis="tp"))
    embed = nn.Embed(config.vocab_size, config.d_model,
                     dtype=config.dtype, param_dtype=config.param_dtype)
    norm = tfm.RMSNorm(dtype=config.dtype)
    positions = jnp.arange(seq_len, dtype=jnp.int32)

    rng = jax.random.PRNGKey(seed)
    rngs = jax.random.split(rng, config.n_layers + 2)
    x0 = jnp.zeros((1, seq_len, config.d_model), config.dtype)
    per_layer = [block.init(rngs[i], x0, positions)["params"]
                 for i in range(config.n_layers)]
    per_stage = [
        pipe.stack_stage_params(
            per_layer[s * layers_per_stage:(s + 1) * layers_per_stage])
        for s in range(num_stages)]
    params = {
        "embed": embed.init(
            rngs[-2], jnp.zeros((1, seq_len), jnp.int32))["params"],
        "stages": pipe.stack_stage_params(per_stage),
        "final_norm": norm.init(rngs[-1], x0)["params"],
    }
    optimizer = optax.adamw(learning_rate, weight_decay=0.01)

    def stage_fn(stage_p, x):
        def layer_step(h, layer_p):
            return apply_block.apply({"params": layer_p}, h,
                                     positions), None
        out, _ = jax.lax.scan(layer_step, x, stage_p)
        return out

    def last_fn(last_p, y, target):
        h = norm.apply({"params": last_p["final_norm"]}, y)
        return tfm.lm_loss_chunked(h, last_p["embedding"], target)

    def stage_leaf_spec(path, leaf):
        """pp on the stage dim; Megatron tp on the feature dims:
        q/k/v/gate/up column-sharded (last dim), o/down row-sharded
        (second-to-last)."""
        name = shard_rules._path_str(path)
        middle = [None] * (leaf.ndim - 2)
        if tp > 1 and leaf.ndim >= 3:
            if any(f"{k}/kernel" in name for k in
                   ("q_proj", "k_proj", "v_proj", "gate_proj",
                    "up_proj")):
                return P("pp", *middle[:-1], None, "tp")
            if any(f"{k}/kernel" in name for k in
                   ("o_proj", "down_proj")):
                return P("pp", *middle[:-1], "tp", None)
        return P("pp", *([None] * (leaf.ndim - 1)))

    stage_specs = jax.tree_util.tree_map_with_path(
        stage_leaf_spec, params["stages"])

    batch_sharding = NamedSharding(mesh, P("dp"))
    param_specs = {
        "embed": jax.tree_util.tree_map(lambda _: P(),
                                        params["embed"]),
        "stages": stage_specs,
        "final_norm": jax.tree_util.tree_map(
            lambda _: P(), params["final_norm"]),
    }
    param_shardings = shard_rules.to_shardings(mesh, param_specs)
    params = jax.device_put(params, param_shardings)
    opt_state = optimizer.init(params)

    def grads_fn(params, tokens, targets):
        h0, embed_vjp = jax.vjp(
            lambda ep: embed.apply({"params": ep}, tokens),
            params["embed"])
        last_params = {"final_norm": params["final_norm"],
                       "embedding": params["embed"]["embedding"]}
        loss, dstages, dlast, dh0 = pipe.pipeline_1f1b_train(
            params["stages"], h0, targets, last_params, mesh=mesh,
            stage_fn=stage_fn, last_fn=last_fn,
            num_microbatches=num_microbatches, batch_axes=("dp",),
            stage_param_specs=stage_specs)
        (dembed,) = embed_vjp(dh0.astype(h0.dtype))
        dembed = {"embedding": dembed["embedding"] +
                  dlast["embedding"].astype(
                      dembed["embedding"].dtype)}
        grads = {"embed": dembed, "stages": dstages,
                 "final_norm": dlast["final_norm"]}
        return loss, grads

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=(param_shardings, None, batch_sharding,
                      batch_sharding),
        out_shardings=(param_shardings, None, None))
    def step(params, opt_state, tokens, targets):
        loss, grads = grads_fn(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        params, opt_state, metrics = step(
            params, opt_state, batch["tokens"], batch["targets"])
        return params, opt_state, metrics

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding)


def build_resnet_train(mesh: Mesh,
                       config: Optional[resnet_mod.ResNetConfig] = None,
                       batch_size: int = 256, image_size: int = 224,
                       learning_rate: float = 0.1,
                       seed: int = 0) -> TrainHarness:
    """Data-parallel ResNet-50 training (the baseline workload)."""
    config = config or resnet_mod.ResNetConfig()
    model = resnet_mod.ResNet(config)
    optimizer = optax.sgd(learning_rate, momentum=0.9, nesterov=True)
    data_spec = P(("dp", "fsdp", "sp", "tp"))
    batch_sharding = NamedSharding(mesh, data_spec)

    def init_fn(rng):
        images = jnp.zeros((batch_size, image_size, image_size, 3),
                           dtype=jnp.float32)
        variables = model.init(rng, images, train=True)
        return variables["params"], variables["batch_stats"]

    rng = jax.random.PRNGKey(seed)
    abstract_params, abstract_stats = jax.eval_shape(init_fn, rng)
    replicated = shard_rules.to_shardings(
        mesh, shard_rules.replicated_specs(abstract_params))
    stats_sharding = shard_rules.to_shardings(
        mesh, shard_rules.replicated_specs(abstract_stats))
    params, batch_stats = jax.jit(
        init_fn, out_shardings=(replicated, stats_sharding))(rng)
    opt_state = optimizer.init(params)

    def loss_fn(params, batch_stats, images, labels):
        logits, updates = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            train=True, mutable=["batch_stats"])
        return resnet_mod.cross_entropy_loss(logits, labels), updates

    @functools.partial(
        jax.jit, donate_argnums=(0, 1, 2),
        in_shardings=(replicated, stats_sharding, None, batch_sharding,
                      batch_sharding),
        out_shardings=(replicated, stats_sharding, None, None))
    def step(params, batch_stats, opt_state, images, labels):
        (loss, updates), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, batch_stats, images, labels)
        new_updates, opt_state = optimizer.update(grads, opt_state,
                                                  params)
        params = optax.apply_updates(params, new_updates)
        return params, updates["batch_stats"], opt_state, {"loss": loss}

    state = {"batch_stats": batch_stats}
    compiled: dict = {}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        params, state["batch_stats"], opt_state, metrics = _aot_step(
            compiled, step, params, state["batch_stats"], opt_state,
            batch["images"], batch["labels"])
        return params, opt_state, metrics

    def precompile():
        # bf16 images are what both the bench and the train_resnet
        # loader feed; a different real dtype falls back to jit.
        images_abs = jax.ShapeDtypeStruct(
            (batch_size, image_size, image_size, 3), jnp.bfloat16,
            sharding=batch_sharding)
        labels_abs = jax.ShapeDtypeStruct((batch_size,), jnp.int32,
                                          sharding=batch_sharding)
        compiled["step"] = step.lower(
            params, state["batch_stats"], opt_state, images_abs,
            labels_abs).compile()

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding,
                        precompile=precompile)


def build_vit_train(mesh: Mesh, config=None, batch_size: int = 256,
                    learning_rate: float = 1e-3,
                    seed: int = 0) -> TrainHarness:
    """ViT image-classification training: data parallel over the batch
    axes with the transformer tp rules applied to the encoder blocks
    (q/k/v/up column-sharded, o/down row-sharded — the param names
    match parallel/sharding's rules by construction)."""
    from batch_shipyard_tpu.models import vit as vit_mod
    config = config or vit_mod.ViTConfig()
    model = vit_mod.ViT(config)
    optimizer = optax.adamw(learning_rate, weight_decay=0.05)
    data_spec = P(("dp", "fsdp", "sp"))
    batch_sharding = NamedSharding(mesh, data_spec)

    def init_fn(rng):
        images = jnp.zeros(
            (batch_size, config.image_size, config.image_size, 3),
            dtype=jnp.float32)
        return model.init(rng, images)["params"]

    rng = jax.random.PRNGKey(seed)
    abstract = jax.eval_shape(init_fn, rng)
    shardings = shard_rules.to_shardings(
        mesh, shard_rules.transformer_param_specs(abstract))
    params = jax.jit(init_fn, out_shardings=shardings)(rng)
    opt_state = optimizer.init(params)

    def loss_fn(params, images, labels):
        logits = model.apply({"params": params}, images)
        return vit_mod.cross_entropy_loss(logits, labels)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=(shardings, None, batch_sharding, batch_sharding),
        out_shardings=(shardings, None, None))
    def step(params, opt_state, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, images,
                                                  labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    compiled: dict = {}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        return _aot_step(compiled, step, params, opt_state,
                         batch["images"], batch["labels"])

    def precompile():
        images_abs = jax.ShapeDtypeStruct(
            (batch_size, config.image_size, config.image_size, 3),
            jnp.float32, sharding=batch_sharding)
        labels_abs = jax.ShapeDtypeStruct((batch_size,), jnp.int32,
                                          sharding=batch_sharding)
        compiled["step"] = step.lower(
            params, opt_state, images_abs, labels_abs).compile()

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding,
                        precompile=precompile)


def build_diffusion_train(mesh: Mesh, config=None,
                          batch_size: int = 256,
                          learning_rate: float = 1e-4,
                          seed: int = 0) -> TrainHarness:
    """DiT denoising-diffusion training. The per-step (t, noise) draws
    come from a PRNG key folded with the step counter inside the jit —
    host code never touches randomness, so the step stays one compiled
    program (batch: {"images": [B,H,W,C] in [-1,1], optional
    "labels": [B]})."""
    from batch_shipyard_tpu.models import diffusion as dif_mod
    config = config or dif_mod.DiTConfig()
    model = dif_mod.DiT(config)
    optimizer = optax.adamw(learning_rate, weight_decay=0.0)
    data_spec = P(("dp", "fsdp", "sp"))
    batch_sharding = NamedSharding(mesh, data_spec)
    labeled = config.num_classes is not None

    def init_fn(rng):
        x = jnp.zeros((batch_size, config.image_size,
                       config.image_size, config.channels),
                      jnp.float32)
        t = jnp.zeros((batch_size,), jnp.int32)
        labels = (jnp.zeros((batch_size,), jnp.int32) if labeled
                  else None)
        return model.init(rng, x, t, labels)["params"]

    rng = jax.random.PRNGKey(seed)
    abstract = jax.eval_shape(init_fn, rng)
    shardings = shard_rules.to_shardings(
        mesh, shard_rules.transformer_param_specs(abstract))
    params = jax.jit(init_fn, out_shardings=shardings)(rng)
    opt_state = optimizer.init(params)
    base_key = jax.random.PRNGKey(seed + 1)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=(shardings, None, batch_sharding,
                      None if not labeled else batch_sharding, None),
        out_shardings=(shardings, None, None))
    def step(params, opt_state, images, labels, step_idx):
        key = jax.random.fold_in(base_key, step_idx)

        def loss_fn(params):
            return dif_mod.diffusion_loss(model, params, images, key,
                                          labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    counter = {"step": 0}
    compiled: dict = {}

    def step_wrapper(params, opt_state, batch):
        # Wedge-watchdog liveness: every step call is one unit of
        # progress (throttled no-op outside pool tasks).
        progress_mod.beat()
        params, opt_state, metrics = _aot_step(
            compiled, step, params, opt_state, batch["images"],
            batch.get("labels"), counter["step"])
        counter["step"] += 1
        return params, opt_state, metrics

    def precompile():
        images_abs = jax.ShapeDtypeStruct(
            (batch_size, config.image_size, config.image_size,
             config.channels), jnp.float32, sharding=batch_sharding)
        labels_abs = (jax.ShapeDtypeStruct(
            (batch_size,), jnp.int32, sharding=batch_sharding)
            if labeled else None)
        # step_idx is a weak-typed python int at every call site;
        # lowering with a concrete 0 matches that signature.
        compiled["step"] = step.lower(
            params, opt_state, images_abs, labels_abs, 0).compile()

    return TrainHarness(mesh=mesh, params=params, opt_state=opt_state,
                        step=step_wrapper,
                        batch_sharding=batch_sharding,
                        precompile=precompile)

"""Continuous batching: a slot-based serving engine over the KV-cache
decode path.

ROADMAP item (the reference has no serving story): instead of
generating whole batches in lockstep (models/inference.generate —
every sequence must finish before any slot frees), the engine holds a
fixed pool of decode SLOTS sharing one batched KV cache. Requests
admit into free slots as they arrive (per-slot prefill via a batch-1
scatter into the big cache), every engine step decodes ONE token for
all active slots in a single jitted call, and finished slots free
immediately for the next request — the throughput property
continuous-batching servers (Orca/vLLM-class) are built around.

TPU-first mechanics: the per-slot cache index ([B] int32,
transformer._decode_attend) lets slots sit at different depths in one
[B, T, H, D] cache; per-slot RoPE positions ride the 2-D positions
path; everything is static-shape jitted — admit/emit bookkeeping is
host-side Python, compute is two compiled functions (prefill, step).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import transformer as tfm


@dataclasses.dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    eos_id: Optional[int] = None


@dataclasses.dataclass
class _Slot:
    request: Optional[Request] = None
    generated: list[int] = dataclasses.field(default_factory=list)


class ContinuousBatcher:
    """Slot-based continuous batching engine.

    Usage:
        engine = ContinuousBatcher(config, params, num_slots=8,
                                   max_decode_len=2048)
        engine.submit(Request("r1", prompt_ids, max_new_tokens=128))
        while engine.pending():
            for request_id, tokens in engine.step():
                ...  # finished request
    """

    def __init__(self, config: tfm.TransformerConfig, params,
                 num_slots: int, max_decode_len: int,
                 sampling: inf.SamplingConfig = inf.SamplingConfig(),
                 seed: int = 0):
        self.config = inf.decode_config(config, max_decode_len)
        self.model = tfm.TransformerLM(self.config)
        self.params = params
        self.num_slots = num_slots
        self.max_decode_len = max_decode_len
        self.sampling = sampling
        self.cache = inf.init_cache(self.model, params, num_slots)
        self._slots = [_Slot() for _ in range(num_slots)]
        self._queue: list[Request] = []
        self._tokens = jnp.zeros((num_slots, 1), jnp.int32)
        self._positions = jnp.zeros((num_slots,), jnp.int32)
        self._active = jnp.zeros((num_slots,), jnp.bool_)
        self._key = jax.random.PRNGKey(seed)

        model = self.model
        sampling_cfg = self.sampling

        @jax.jit
        def decode_step(params, cache, tokens, positions, active, key):
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens,
                positions=positions[:, None], mutable=["cache"])
            next_tok = inf._sample(logits[:, 0].astype(jnp.float32),
                                   key, sampling_cfg)
            # Inactive slots DO write garbage into their cache rows,
            # and that is fine: a freed row is never read (the
            # per-slot mask excludes other rows) and _admit's prefill
            # rewrites the whole row + index before reuse — restoring
            # the full K/V trees here would double per-token HBM
            # traffic for no observable effect. Only the cheap token/
            # position bookkeeping needs masking.
            next_tok = jnp.where(active, next_tok, tokens[:, 0])
            positions = jnp.where(active, positions + 1, positions)
            return (mutated["cache"], next_tok[:, None], positions,
                    next_tok)

        self._decode_step = decode_step

        @functools.partial(jax.jit, static_argnames=("prompt_len",))
        def prefill(params, cache, slot, prompt, prompt_len):
            """Fill ONE slot's cache region from a prompt [1, L]
            (batch-1 forward, scattered into the slot row), returning
            the last-token logits for the first sample."""
            small = inf.init_cache(model, params, 1)

            def body(carry, tok):
                c, pos = carry
                logits, mut = model.apply(
                    {"params": params, "cache": c}, tok[None, None],
                    positions=pos[None], mutable=["cache"])
                return (mut["cache"], pos + 1), logits[0, 0]

            (small, _pos), logits_seq = jax.lax.scan(
                body, (small, jnp.int32(0)), prompt[0, :prompt_len])
            cache = jax.tree_util.tree_map(
                lambda big, sm: big.at[slot].set(sm[0]), cache, small)
            return cache, logits_seq[-1]

        self._prefill = prefill

    # ------------------------------ public -----------------------------

    def submit(self, request: Request) -> None:
        if request.max_new_tokens < 1:
            raise ValueError(
                f"{request.request_id}: max_new_tokens must be >= 1")
        if len(request.prompt) + request.max_new_tokens > \
                self.max_decode_len:
            raise ValueError(
                f"{request.request_id}: prompt+generation "
                f"{len(request.prompt)}+{request.max_new_tokens} "
                f"exceeds max_decode_len {self.max_decode_len}")
        self._queue.append(request)

    def pending(self) -> int:
        return len(self._queue) + sum(
            1 for s in self._slots if s.request is not None)

    def step(self) -> list[tuple[str, list[int]]]:
        """Admit queued requests into free slots, decode one token for
        every active slot, emit finished requests."""
        self._admit()
        # Slots whose prefill-sampled first token already satisfied the
        # request (max_new_tokens == 1 or immediate eos) emit without a
        # decode step.
        emitted: list[tuple[str, list[int]]] = []
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None or not slot.generated:
                continue
            last = slot.generated[-1]
            if (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and last == req.eos_id)):
                emitted.append((req.request_id, list(slot.generated)))
                self._slots[i] = _Slot()
                self._active = self._active.at[i].set(False)
        if not any(s.request is not None for s in self._slots):
            return emitted
        self._key, step_key = jax.random.split(self._key)
        self.cache, self._tokens, self._positions, next_tok = \
            self._decode_step(self.params, self.cache, self._tokens,
                              self._positions, self._active, step_key)
        next_host = np.asarray(next_tok)
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            token = int(next_host[i])
            slot.generated.append(token)
            done = (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and token == req.eos_id))
            if done:
                emitted.append((req.request_id, list(slot.generated)))
                self._slots[i] = _Slot()
                self._active = self._active.at[i].set(False)
        return emitted

    # ----------------------------- internal ----------------------------

    def _admit(self) -> None:
        for i, slot in enumerate(self._slots):
            if slot.request is not None or not self._queue:
                continue
            req = self._queue.pop(0)
            prompt = jnp.asarray([req.prompt], jnp.int32)
            self.cache, last_logits = self._prefill(
                self.params, self.cache, i, prompt, len(req.prompt))
            self._key, sample_key = jax.random.split(self._key)
            first = inf._sample(
                last_logits[None].astype(jnp.float32), sample_key,
                self.sampling)
            # The prefill-sampled token IS the first generated token.
            self._slots[i] = _Slot(request=req,
                                   generated=[int(first[0])])
            self._tokens = self._tokens.at[i, 0].set(first[0])
            self._positions = self._positions.at[i].set(
                len(req.prompt))
            self._active = self._active.at[i].set(True)

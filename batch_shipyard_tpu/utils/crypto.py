"""Crypto utilities: ssh keypairs, on-node credential protection, ssh
exec helpers.

Reference analog: convoy/crypto.py — ssh keypair gen (:127), PEM/PFX
cert derivation via openssl subprocess (:219-434), RSA
encrypt/decrypt of credentials for on-node env (:535-615), ssh
connect/exec helper (:171). Re-built on the ``cryptography`` library
(no openssl subprocess needed) with the same capability surface.
"""

from __future__ import annotations

import base64
import os
import subprocess
from typing import Optional, Sequence

# The cryptography wheel is absent from some accelerator containers;
# gate it so importing this module (and everything that transitively
# pulls utils) stays possible — the key/credential helpers raise a
# clear error at CALL time instead.
try:
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa
    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover - environment-dependent
    hashes = serialization = padding = rsa = None
    HAVE_CRYPTOGRAPHY = False


def _require_cryptography() -> None:
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "the 'cryptography' package is not installed in this "
            "environment; ssh keypair / credential encryption "
            "helpers are unavailable")

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def generate_ssh_keypair(output_dir: str,
                         name: str = "id_rsa_shipyard",
                         bits: int = 3072) -> tuple[str, str]:
    """Generate an RSA ssh keypair; returns (private_path,
    public_path). (reference crypto.py:127)"""
    _require_cryptography()
    key = rsa.generate_private_key(public_exponent=65537, key_size=bits)
    private_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.OpenSSH,
        serialization.NoEncryption())
    public_ssh = key.public_key().public_bytes(
        serialization.Encoding.OpenSSH,
        serialization.PublicFormat.OpenSSH)
    os.makedirs(output_dir, exist_ok=True)
    private_path = os.path.join(output_dir, name)
    public_path = private_path + ".pub"
    with open(private_path, "wb") as fh:
        fh.write(private_pem)
    os.chmod(private_path, 0o600)
    with open(public_path, "wb") as fh:
        fh.write(public_ssh + b"\n")
    return private_path, public_path


def generate_rsa_keypair_pem(bits: int = 3072) -> tuple[bytes, bytes]:
    """(private_pem, public_pem) for credential encryption."""
    _require_cryptography()
    key = rsa.generate_private_key(public_exponent=65537, key_size=bits)
    private_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    public_pem = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)
    return private_pem, public_pem


def encrypt_credential(public_pem: bytes, plaintext: str) -> str:
    """RSA-OAEP encrypt a short credential for on-node decryption
    (reference crypto.py:535 encrypt via cert)."""
    _require_cryptography()
    public = serialization.load_pem_public_key(public_pem)
    ciphertext = public.encrypt(
        plaintext.encode("utf-8"),
        padding.OAEP(mgf=padding.MGF1(hashes.SHA256()),
                     algorithm=hashes.SHA256(), label=None))
    return base64.b64encode(ciphertext).decode("ascii")


def decrypt_credential(private_pem: bytes, encrypted_b64: str) -> str:
    _require_cryptography()
    private = serialization.load_pem_private_key(private_pem, None)
    plaintext = private.decrypt(
        base64.b64decode(encrypted_b64),
        padding.OAEP(mgf=padding.MGF1(hashes.SHA256()),
                     algorithm=hashes.SHA256(), label=None))
    return plaintext.decode("utf-8")


def ssh_command(ip: str, port: int = 22, username: str = "shipyard",
                private_key_file: Optional[str] = None,
                command: Optional[str] = None,
                extra_options: Sequence[str] = (),
                host_key_checking: str = "accept-new") -> list[str]:
    """Build an ssh argv (reference crypto.py:171 connect helper).

    host_key_checking: OpenSSH StrictHostKeyChecking value. The
    default 'accept-new' is trust-on-first-use — unlike the
    reference's unconditional 'no', a changed host key (MITM) is
    rejected; pass 'no' explicitly for throwaway nodes.
    """
    argv = ["ssh", "-o", f"StrictHostKeyChecking={host_key_checking}",
            "-p", str(port)]
    if host_key_checking == "no":
        argv[3:3] = ["-o", "UserKnownHostsFile=/dev/null"]
    if private_key_file:
        argv += ["-i", private_key_file]
    argv += list(extra_options)
    argv.append(f"{username}@{ip}")
    if command:
        argv.append(command)
    return argv


def ssh_exec(ip: str, command: str, port: int = 22,
             username: str = "shipyard",
             private_key_file: Optional[str] = None,
             timeout: float = 60.0) -> tuple[int, str, str]:
    argv = ssh_command(ip, port, username, private_key_file, command)
    return util.subprocess_capture(argv, timeout=timeout)


def ssh_tunnel_script(ip: str, port: int, local_port: int,
                      remote_port: int, username: str,
                      private_key_file: Optional[str],
                      output_path: str) -> str:
    """Write an ssh tunnel helper script (reference batch.py:1095 ssh
    tunnel script gen; used for tensorboard/grafana tunnels)."""
    key_arg = f"-i {private_key_file} " if private_key_file else ""
    script = (
        "#!/usr/bin/env bash\n"
        "set -e\n"
        f"exec ssh -o StrictHostKeyChecking=accept-new "
        f"{key_arg}-p {port} "
        f"-N -L {local_port}:localhost:{remote_port} "
        f"{username}@{ip}\n")
    with open(output_path, "w", encoding="utf-8") as fh:
        fh.write(script)
    os.chmod(output_path, 0o755)
    return output_path

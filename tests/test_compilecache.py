"""Warm-start compilation: cache identity honesty, hit/saved-seconds
tracking, pool-wide seeding round trips through the state store, the
all-bucket serving warm-up, and the fakepod e2e where task 1 compiles
cold + exports the seed and task 2 runs warm with
``compile_saved_seconds > 0``."""

import json
import os
import pathlib
import time

import pytest

from batch_shipyard_tpu.compilecache import manager, seeding
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as gp
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]

_ID_ARGS = dict(jax_version="0.4.37", jaxlib_version="0.4.36",
                backend="tpu", device_kind="TPU v5e",
                device_count=8, process_count=2,
                mesh_shape={"dp": 4, "tp": 2},
                model_digest="abc123")


# --------------------------- identity key ------------------------------

def test_identity_key_stable_for_identical_inputs():
    """Pure over explicit inputs: the same config produces the same
    key in any process (no object reprs, no clocks, no randomness)."""
    assert manager.identity_key(**_ID_ARGS) == \
        manager.identity_key(**dict(_ID_ARGS))


@pytest.mark.parametrize("field,value", [
    ("jax_version", "0.5.0"),
    ("jaxlib_version", "0.5.0"),
    ("backend", "cpu"),
    ("device_kind", "TPU v4"),
    ("device_count", 16),
    ("process_count", 4),
    ("mesh_shape", {"dp": 2, "tp": 4}),
    ("model_digest", "def456"),
])
def test_identity_key_changes_per_dimension(field, value):
    changed = dict(_ID_ARGS, **{field: value})
    assert manager.identity_key(**changed) != \
        manager.identity_key(**_ID_ARGS)


def _attention(q, k, v, causal):
    return q


def test_config_digest_stable_and_sensitive():
    """Equal configs digest identically even across instances holding
    callables (no memory addresses leak in); any field change changes
    the digest."""
    import dataclasses

    @dataclasses.dataclass
    class Cfg:
        d_model: int = 64
        fn: object = _attention

    assert manager.config_digest(Cfg()) == manager.config_digest(Cfg())
    assert manager.config_digest(Cfg(d_model=128)) != \
        manager.config_digest(Cfg())
    # Raw-object fallback reprs get their addresses scrubbed.
    class Opaque:
        pass

    assert manager.config_digest({"x": Opaque()}) == \
        manager.config_digest({"x": Opaque()})


# ------------------------ track: hit/miss/saved ------------------------

def _fake_compile(mgr, label, entry, cold_sleep=0.05):
    with mgr.track(label) as result:
        path = os.path.join(mgr.cache_dir, entry)
        if not os.path.exists(path):
            time.sleep(cold_sleep)
            with open(path, "w", encoding="utf-8") as fh:
                fh.write("x" * 2048)
    return result


def test_track_records_miss_then_hit_with_saved_seconds(tmp_path):
    mgr = manager.enable(str(tmp_path / "cache"), identity="idA",
                         configure_jax=False)
    cold = _fake_compile(mgr, "step", "step-cache")
    assert cold["cache_hit"] is False and cold["new_entries"] == 1
    assert cold["saved_seconds"] == 0.0
    # A warm RESTART is a fresh process = a fresh manager over the
    # same dir: the hit is priced against the remembered cold wall.
    mgr = manager.enable(str(tmp_path / "cache"), identity="idA",
                         configure_jax=False)
    warm = _fake_compile(mgr, "step", "step-cache")
    assert warm["cache_hit"] is True and warm["new_entries"] == 0
    assert warm["saved_seconds"] > 0.0
    stats = mgr.stats()
    assert stats["hits"] == 1 and stats["misses"] == 0
    assert stats["saved_seconds"] > 0.0


def test_track_repeat_label_is_not_a_persistent_hit(tmp_path):
    """Replica engines 2..N reuse replica 1's in-process jits: a
    repeat of a label within one process must be reported as reuse —
    neither a hit (no multiplied compile_saved_seconds) nor a
    miss."""
    mgr = manager.enable(str(tmp_path / "cache"), identity="idA",
                         configure_jax=False)
    _fake_compile(mgr, "warmup", "warm-cache")
    repeat = _fake_compile(mgr, "warmup", "warm-cache")
    assert repeat["in_process_reuse"] is True
    assert repeat["cache_hit"] is False
    assert repeat["saved_seconds"] == 0.0
    assert mgr.stats() == {**mgr.stats(), "hits": 0, "misses": 1}
    # tracked() stamps nothing for a reuse — the goodput event must
    # not count as a miss either.
    attrs = {}
    with manager.tracked(attrs, "warmup"):
        pass
    assert "cache_hit" not in attrs


def test_tracked_stamps_goodput_attrs(tmp_path):
    mgr = manager.enable(str(tmp_path / "cache"), identity="idA",
                         configure_jax=False)
    _fake_compile(mgr, "warmup", "warm-cache")
    manager.enable(str(tmp_path / "cache"), identity="idA",
                   configure_jax=False)  # fresh process analog
    attrs = {}
    with manager.tracked(attrs, "warmup"):
        pass  # everything already cached
    assert attrs["cache_hit"] is True
    assert attrs["saved_seconds"] >= 0.0


def test_identities_coexist_under_one_root(tmp_path):
    """A mixed pool's node dir holds one namespaced subdir per
    identity: enabling identity B must NOT disturb identity A's warm
    entries (the thrash a single shared dir would cause)."""
    root = str(tmp_path / "cache")
    mgr_a = manager.enable(root, identity="idA", configure_jax=False)
    _fake_compile(mgr_a, "step", "step-cache")
    mgr_b = manager.enable(root, identity="idB", configure_jax=False)
    assert mgr_b.entries() == {}
    assert mgr_a.entries() != {}
    assert mgr_a.cache_dir != mgr_b.cache_dir
    dirs = manager.list_identity_dirs(root)
    assert sorted(dirs) == ["idA", "idB"]
    assert manager.read_identity(dirs["idA"]) == "idA"
    assert manager.read_identity(dirs["idB"]) == "idB"


def test_enable_configures_real_jax_persistent_cache(tmp_path):
    """The real integration: enable() + one tiny jit writes entries
    into the dir (thresholds dropped to zero so CPU-test compiles
    land)."""
    import jax
    import jax.numpy as jnp
    cache = str(tmp_path / "jaxcache")
    mgr = manager.enable(cache)
    try:
        with mgr.track("tiny") as result:
            jax.jit(lambda x: x * 2 + 1)(
                jnp.arange(8.0)).block_until_ready()
        assert result["new_entries"] >= 1
        assert mgr.entries()
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


# ------------------------------ seeding --------------------------------

def _seeded_store(tmp_path, identity="idA"):
    store = MemoryStateStore()
    cache = str(tmp_path / "node-a")
    mgr = manager.enable(cache, identity=identity,
                         configure_jax=False)
    _fake_compile(mgr, "step", "step-cache")
    _fake_compile(mgr, "prefill", "prefill-cache")
    assert seeding.export_cache(store, "pool1", cache, "node-a")
    return store, cache


def test_export_seed_round_trip_hits_on_fresh_node(tmp_path):
    store, _cache = _seeded_store(tmp_path)
    latest = seeding.latest_info(store, "pool1")
    assert latest["identities"]["idA"]["entries"] == 2
    fresh = str(tmp_path / "node-b")
    assert seeding.seed_cache(store, "pool1", fresh) == \
        seeding.SEEDED
    # The seeded node's next "compile" is a warm hit WITH a priced
    # saving: the cold times travel in the meta sidecar.
    mgr = manager.enable(fresh, identity="idA", configure_jax=False)
    warm = _fake_compile(mgr, "step", "step-cache")
    assert warm["cache_hit"] is True
    assert warm["saved_seconds"] > 0.0


def test_seed_refuses_unpublished_pinned_identity(tmp_path):
    store, _cache = _seeded_store(tmp_path)
    fresh = str(tmp_path / "node-c")
    assert seeding.seed_cache(
        store, "pool1", fresh,
        expected_identity="idOTHER") == seeding.REFUSED
    assert manager.list_identity_dirs(fresh) == {}
    # Unpinned, a mixed-identity node seeds ONLY into the published
    # identity's subdir; a foreign subdir is never polluted.
    mixed = str(tmp_path / "node-d")
    manager.enable(mixed, identity="idOTHER", configure_jax=False)
    assert seeding.seed_cache(store, "pool1",
                              mixed) == seeding.SEEDED
    assert manager.snapshot(
        manager.identity_subdir(mixed, "idOTHER")) == {}
    assert "step-cache" in manager.snapshot(
        manager.identity_subdir(mixed, "idA"))


def test_export_handles_mixed_identities(tmp_path):
    """Two workload types on one node export under their own
    identities; the pool map keeps BOTH pointers live."""
    store, cache = _seeded_store(tmp_path)
    mgr_b = manager.enable(cache, identity="idB",
                           configure_jax=False)
    _fake_compile(mgr_b, "other", "other-cache")
    assert seeding.export_cache(store, "pool1", cache,
                                "node-a") is not None
    identities = seeding.latest_info(store, "pool1")["identities"]
    assert identities["idA"]["entries"] == 2
    assert identities["idB"]["entries"] == 1


def test_export_skips_when_pool_has_equal_or_newer(tmp_path):
    store, cache = _seeded_store(tmp_path)
    # Same identity, same entry count: nothing newer to publish.
    assert seeding.export_cache(store, "pool1", cache,
                                "node-a") is None
    # A third entry makes it newer again.
    mgr = manager.enable(cache, identity="idA", configure_jax=False)
    _fake_compile(mgr, "decode", "decode-cache")
    assert seeding.export_cache(store, "pool1", cache,
                                "node-a") is not None
    assert seeding.latest_info(
        store, "pool1")["identities"]["idA"]["entries"] == 3


def test_export_respects_the_lease(tmp_path):
    store = MemoryStateStore()
    cache = str(tmp_path / "node-a")
    mgr = manager.enable(cache, identity="idA", configure_jax=False)
    _fake_compile(mgr, "step", "step-cache")
    from batch_shipyard_tpu.state import names
    held = store.acquire_lease(
        names.compile_cache_lease_key("pool1", "idA"), 30.0, "other")
    assert held is not None
    assert seeding.export_cache(store, "pool1", cache,
                                "node-a") is None
    store.release_lease(held)
    assert seeding.export_cache(store, "pool1", cache,
                                "node-a") is not None


def test_seed_skips_when_local_is_as_warm(tmp_path):
    store, cache = _seeded_store(tmp_path)
    assert seeding.seed_cache(store, "pool1", cache) == seeding.SKIP
    assert seeding.seed_cache(MemoryStateStore(), "pool1",
                              cache) == seeding.ABSENT


def test_prune_and_stats(tmp_path):
    store, _cache = _seeded_store(tmp_path)
    report = seeding.stats(store, "pool1")
    assert report["identities"]["idA"]["entries"] == 2
    assert len(report["artifacts"]) == 1
    removed = seeding.prune(store, "pool1")
    assert removed == 2  # tar + latest.json
    assert seeding.latest_info(store, "pool1") is None
    assert seeding.stats(store, "pool1")["artifacts"] == []


def test_seed_rejects_traversal_members(tmp_path):
    """A hostile artifact cannot write outside the cache dir."""
    import io
    import tarfile
    store = MemoryStateStore()
    buffer = io.BytesIO()
    with tarfile.open(fileobj=buffer, mode="w") as tar:
        data = b"evil"
        for name in ("../escape-cache", "sub/dir-cache",
                     "ok-cache"):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    from batch_shipyard_tpu.state import names
    store.put_object(names.compile_cache_key("pool1", "idA"),
                     buffer.getvalue())
    store.put_object(
        names.compile_cache_latest_key("pool1"),
        json.dumps({"identities": {"idA": {
            "entries": 3,
            "key": names.compile_cache_key("pool1", "idA"),
        }}}).encode())
    target = str(tmp_path / "seedme")
    assert seeding.seed_cache(store, "pool1",
                              target) == seeding.SEEDED
    subdir = manager.identity_subdir(target, "idA")
    assert sorted(manager.snapshot(subdir)) == ["ok-cache"]
    assert not (tmp_path / "escape-cache").exists()
    assert not (tmp_path / "seedme" / "escape-cache").exists()


# ---------------------- serving warm-up buckets ------------------------

def test_serving_warmup_warms_every_bucket(tmp_path, monkeypatch):
    """Satellite: warm-up no longer compiles only the 16-token bucket
    — every configured bucket up to max_decode_len is driven, so the
    first long-prompt request never pays a mid-traffic compile; the
    goodput warm-up event carries the cache detail."""
    import jax
    import jax.numpy as jnp

    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    events_file = tmp_path / "goodput.jsonl"
    monkeypatch.setenv(gp.GOODPUT_FILE_ENV, str(events_file))
    # The standard serving-test config: the engine's jits are
    # module-level static-model compiles, so this test PRE-PAYS the
    # decode/bucket-16 compiles that tests/test_serving.py (later in
    # the alphabet) reuses — only the longer buckets are net-new
    # suite cost.
    config = tfm.TransformerConfig(
        vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    params = tfm.TransformerLM(config).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    engine = serving.ContinuousBatcher(config, params, num_slots=2,
                                       max_decode_len=64)
    assert engine.warmup_buckets() == [16, 32, 64]
    assert engine.warmup() == [16, 32, 64]
    assert engine.pending() == 0
    recorded = [json.loads(line) for line in
                events_file.read_text().splitlines()]
    warm = [e for e in recorded if e["kind"] == gp.PROGRAM_WARMUP]
    assert warm and warm[-1]["attrs"]["buckets"] == 3
    # Legacy single-length warm-up still available.
    assert engine.warmup(prompt_len=4) == [16]


# --------------------------- e2e on fakepod ----------------------------

_E2E_PAYLOAD = """
import os, sys, time
sys.path.insert(0, {repo!r})
from batch_shipyard_tpu.compilecache import manager
from batch_shipyard_tpu.goodput import events
mgr = manager.enable(os.environ["SHIPYARD_COMPILE_CACHE_DIR"],
                     identity="e2e-fixed", configure_jax=False)
with events.phase(events.PROGRAM_COMPILE, what="probe") as attrs:
    with manager.tracked(attrs, "probe"):
        entry = os.path.join(mgr.cache_dir, "probe-entry-cache")
        if os.path.exists(entry):
            time.sleep(0.02)   # warm: cache deserialization cost
        else:
            time.sleep(0.35)   # cold: the full "XLA compile"
            with open(entry, "w", encoding="utf-8") as fh:
                fh.write("x" * 4096)
start = time.time()
time.sleep(0.08)
events.record(events.PROGRAM_STEP_WINDOW, start, time.time(),
              step_start=0, step_end=4, tokens=32)
"""


@pytest.fixture()
def fakepod_env():
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    conf = {"pool_specification": {
        "id": "cachepool", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16", "num_slices": 1},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool,
                         settings_mod.global_settings({}), conf)
    yield store, substrate, pool, jobs_mgr
    substrate.stop_all()


def _partition_is_exact(report):
    total = report["productive_seconds"] + \
        sum(report["badput_seconds"].values()) + \
        sum(report["overlapped_seconds"].values())
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)


def test_e2e_second_task_runs_warm_and_reports_savings(
        fakepod_env, tmp_path):
    """Satellite acceptance: two sequential tasks on one pool. Task 1
    cold-compiles and the agent exports the pool seed; task 2 runs
    warm (locally or via seeding) and reports
    ``compile_saved_seconds > 0`` with compile badput strictly lower,
    while the wall-clock partition stays exact for both jobs."""
    store, substrate, pool, jobs_mgr = fakepod_env
    script = tmp_path / "payload.py"
    script.write_text(_E2E_PAYLOAD.format(repo=str(REPO_ROOT)),
                      encoding="utf-8")
    for job_id in ("jcold", "jwarm"):
        jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
            {"job_specifications": [{
                "id": job_id,
                "tasks": [{"command": f"python3 {script}"}]}]}))
        tasks = jobs_mgr.wait_for_tasks(store, "cachepool", job_id,
                                        timeout=30)
        assert tasks[0]["state"] == "completed", tasks[0]
        # The agent's export runs on a background thread after the
        # task; wait for the artifact so job 2 is guaranteed a seed
        # whichever node it lands on.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                seeding.latest_info(store, "cachepool") is None:
            time.sleep(0.05)
    latest = seeding.latest_info(store, "cachepool")
    assert latest is not None
    assert latest["identities"]["e2e-fixed"]["entries"] >= 1

    def _wait_report(job_id):
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            events = gp.query(store, "cachepool", job_id=job_id)
            if any(e["kind"] == gp.PROGRAM_COMPILE for e in events):
                break
            time.sleep(0.1)
        return accounting.job_report(store, "cachepool", job_id)

    cold = _wait_report("jcold")
    warm = _wait_report("jwarm")
    assert cold["compile_cache_misses"] >= 1
    assert cold["compile_saved_seconds"] == 0.0
    assert warm["compile_cache_hits"] >= 1
    assert warm["compile_saved_seconds"] > 0.0
    assert warm["badput_seconds"]["compile"] < \
        cold["badput_seconds"]["compile"]
    _partition_is_exact(cold)
    _partition_is_exact(warm)
    # Pool rollup and prometheus surface the saving.
    pool_rep = accounting.pool_report(store, "cachepool")
    assert pool_rep["compile_saved_seconds"] > 0.0
    lines = accounting.prometheus_lines(pool_rep,
                                        {"pool": "cachepool"})
    assert any(line.startswith("goodput_compile_saved_seconds")
               for line in lines)
    # A genuinely fresh node (empty dir) seeds from the exported
    # artifact and holds the warm entry; a mismatched node refuses.
    fresh = str(tmp_path / "fresh-node")
    assert seeding.seed_cache(
        store, "cachepool", fresh,
        expected_identity="e2e-fixed") == seeding.SEEDED
    assert "probe-entry-cache" in manager.snapshot(
        manager.identity_subdir(fresh, "e2e-fixed"))
    assert seeding.seed_cache(
        store, "cachepool", str(tmp_path / "mismatched"),
        expected_identity="other") == seeding.REFUSED

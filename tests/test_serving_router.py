"""Serving fleet router (VERDICT r4 next #6): queue-depth-aware
dispatch across replica front ends, health-check rotation, failover,
sticky cancel, streaming passthrough, and loadgen-through-router."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from batch_shipyard_tpu.models import loadgen, serving
from batch_shipyard_tpu.models import transformer as tfm
from batch_shipyard_tpu.models.router import ServingRouter
from batch_shipyard_tpu.models.server import ServingFrontEnd

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    model = tfm.TransformerLM(CFG)
    return model.init(jax.random.PRNGKey(7),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _front(params):
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    return ServingFrontEnd(engine, port=0).start()


@pytest.fixture()
def fleet(params):
    fronts = [_front(params), _front(params)]
    router = ServingRouter([f.url for f in fronts],
                           health_interval=0.2).start()
    yield router, fronts
    router.shutdown()
    for f in fronts:
        try:
            f.shutdown()
        except Exception:
            pass


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        f"{url}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _get(url, path):
    with urllib.request.urlopen(f"{url}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def test_router_dispatches_and_balances(fleet):
    router, fronts = fleet
    seen = set()
    for k in range(4):
        out = _post(router.url, {"prompt": [1 + k, 2, 3],
                                 "max_new_tokens": 3})
        assert out["num_tokens"] == 3
        seen.add(out["_replica"])
    # Sequential idle-fleet requests alternate via the dispatched
    # tie-break: both replicas must have served.
    assert seen == {f.url for f in fronts}
    status, stats = _get(router.url, "/v1/stats")
    assert status == 200
    assert stats["completed"] == 4
    assert stats["healthy_replicas"] == 2
    assert all(s["completed"] >= 1 for s in stats["per_replica"])


def test_router_prefers_less_loaded_replica(fleet):
    router, _fronts = fleet
    # Occupy one replica with a long generation; concurrent short
    # requests must land on the other.
    long_done = {}

    def _long():
        long_done["r"] = _post(router.url, {
            "request_id": "long-run", "prompt": [9, 9, 9],
            "max_new_tokens": 40})

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    # Wait until the router has the long run in flight.
    deadline = time.monotonic() + 20
    busy_url = None
    while time.monotonic() < deadline and busy_url is None:
        for snap in router.replicas():
            if snap["inflight"] > 0:
                busy_url = snap["url"]
        time.sleep(0.01)
    assert busy_url is not None
    short = _post(router.url, {"prompt": [4, 5], "max_new_tokens": 2})
    assert short["_replica"] != busy_url
    t.join(120)
    assert long_done["r"]["num_tokens"] == 40


def test_router_health_failover_and_503(fleet):
    router, fronts = fleet
    fronts[1].shutdown()
    # Next probe cycle marks it unhealthy.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and router.healthy_count() != 1:
        time.sleep(0.05)
    assert router.healthy_count() == 1
    status, health = _get(router.url, "/healthz")
    assert status == 200 and health["healthy_replicas"] == 1
    # All traffic now goes to the survivor.
    for _ in range(3):
        out = _post(router.url, {"prompt": [1, 2],
                                 "max_new_tokens": 2})
        assert out["_replica"] == fronts[0].url
    fronts[0].shutdown()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and router.healthy_count():
        time.sleep(0.05)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(router.url, {"prompt": [1], "max_new_tokens": 1})
    assert exc.value.code == 503


def test_router_dispatch_failover_marks_unhealthy(fleet, params):
    """A replica that dies between probes: the dispatch itself fails
    over and flags it."""
    router, fronts = fleet
    victim = fronts[1]
    victim.shutdown()  # dies silently; probe hasn't run yet
    with router._lock:
        for r in router._replicas:
            r.healthy = True  # simulate stale healthy state
    for _ in range(4):
        out = _post(router.url, {"prompt": [3, 1],
                                 "max_new_tokens": 2})
        assert out["_replica"] == fronts[0].url
    snaps = {s["url"]: s for s in router.replicas()}
    assert snaps[victim.url]["healthy"] is False


def _poll(predicate, deadline_s: float = 60.0, interval: float = 0.02):
    """Poll-with-deadline (VERDICT r5 #7): on a saturated box any
    single fixed timeout flakes; the loop retries until the condition
    holds or the generous deadline expires."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def test_router_sticky_cancel(fleet):
    router, _fronts = fleet
    result = {}

    def _long():
        try:
            result["r"] = _post(router.url, {
                "request_id": "cancel-me", "prompt": [7, 7],
                "max_new_tokens": 60}, timeout=240)
        except urllib.error.HTTPError as exc:
            result["code"] = exc.code
            result["body"] = json.loads(exc.read())

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    assert _poll(lambda: "cancel-me" in router._owner)
    # The owner mapping can exist before the replica has the run
    # registered (the POST is still in flight to it): poll the DELETE
    # until the owner answers 202 rather than asserting the first
    # attempt.
    cancel_result = {}

    def _cancelled():
        code, payload = router.cancel("cancel-me")
        cancel_result["code"] = code
        return code == 202

    assert _poll(_cancelled, deadline_s=60.0), cancel_result
    assert _poll(lambda: "code" in result or "r" in result,
                 deadline_s=120.0)
    t.join(10)
    # The replica completes the waiter with 409 cancelled.
    assert result.get("code") == 409, result
    assert "cancelled" in result["body"]["error"]


def test_router_broadcast_cancel_finds_unknown_owner(fleet):
    """A request the router never dispatched (server-assigned or
    submitted directly to a replica): broadcast probes replicas —
    non-owners 404, the owner 202s."""
    router, fronts = fleet
    result = {}

    def _long():
        try:
            result["r"] = _post(fronts[1].url, {
                "request_id": "direct-long", "prompt": [8, 8],
                "max_new_tokens": 60})
        except urllib.error.HTTPError as exc:
            result["code"] = exc.code

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            not fronts[1].knows("direct-long"):
        time.sleep(0.01)
    assert "direct-long" not in router._owner
    code, payload = router.cancel("direct-long")
    assert code == 202, payload
    t.join(60)
    assert result.get("code") == 409
    # A fully unknown id 404s everywhere.
    code, payload = router.cancel("never-existed")
    assert code == 404


def test_router_rejects_duplicate_inflight_request_id(fleet):
    """A retry of a live id must not land on the OTHER replica and
    decode twice — the router gates ids fleet-wide (the per-replica
    front end can only see its own)."""
    router, _fronts = fleet
    result = {}

    def _long():
        result["r"] = _post(router.url, {
            "request_id": "dup-id", "prompt": [6, 6],
            "max_new_tokens": 50})

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            "dup-id" not in router._owner:
        time.sleep(0.01)
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(router.url, {"request_id": "dup-id", "prompt": [1],
                           "max_new_tokens": 1})
    assert exc.value.code == 400
    assert "in flight" in json.loads(exc.value.read())["error"]
    t.join(120)
    assert result["r"]["num_tokens"] == 50
    # After completion the id is reusable.
    out = _post(router.url, {"request_id": "dup-id", "prompt": [2],
                             "max_new_tokens": 1})
    assert out["num_tokens"] == 1


def test_router_timeout_orphans_and_reconciles(params):
    """A dispatch that outlives request_timeout: 504 to the caller,
    NO re-dispatch (the run may still be live), the id stays gated
    until the health loop sees the replica forget it."""
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    # Deterministic slowness: every engine step pays a fixed delay,
    # so a 50-token decode is guaranteed to outlive the 2s timeout.
    orig_step = engine.step
    engine.step = lambda: (time.sleep(0.1), orig_step())[1]
    fronts = [ServingFrontEnd(engine, port=0).start()]
    router = ServingRouter([fronts[0].url], health_interval=0.2,
                           request_timeout=2.0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(router.url, {"request_id": "slow", "prompt": [3, 3],
                               "max_new_tokens": 50})
        assert exc.value.code == 504
        # Still owned: a retry is refused while the run may be live.
        assert "slow" in router._owner
        with pytest.raises(urllib.error.HTTPError) as exc2:
            _post(router.url, {"request_id": "slow", "prompt": [1],
                               "max_new_tokens": 1})
        assert exc2.value.code == 400
        # Once the replica finishes (or we cancel) and forgets the
        # id, reconciliation releases it.
        fronts[0].cancel("slow")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and \
                "slow" in router._owner:
            time.sleep(0.05)
        assert "slow" not in router._owner
        out = _post(router.url, {"request_id": "slow", "prompt": [2],
                                 "max_new_tokens": 1})
        assert out["num_tokens"] == 1
    finally:
        router.shutdown()
        fronts[0].shutdown()


def test_router_streaming_passthrough(fleet):
    router, _fronts = fleet
    req = urllib.request.Request(
        f"{router.url}/v1/generate",
        data=json.dumps({"prompt": [5, 6, 7], "max_new_tokens": 4,
                         "stream": True}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert resp.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(line) for line in resp if line.strip()]
    tokens = [ln for ln in lines if "token" in ln]
    finals = [ln for ln in lines if "tokens" in ln]
    assert len(tokens) == 4
    assert len(finals) == 1 and finals[0]["num_tokens"] == 4


def test_loadgen_through_router(fleet):
    router, _fronts = fleet
    report = loadgen.run_load(router.url, num_requests=8,
                              rate_hz=50.0, prompt_len=(2, 6),
                              max_new_tokens=(2, 5), vocab_size=97,
                              seed=3)
    assert report["completed"] == 8
    assert report["failed"] == 0
    assert report["generated_tokens"] > 0
    status, stats = _get(router.url, "/v1/stats")
    assert stats["completed"] >= 8

def test_prometheus_metrics_endpoints(fleet):
    """Front end and router expose Prometheus text metrics the
    monitoring stack can scrape (docs/09-monitoring.md)."""
    router, fronts = fleet
    _post(router.url, {"prompt": [4, 2], "max_new_tokens": 3})

    def scrape(url):
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith(
                "text/plain")
            return resp.read().decode()

    front_text = scrape(fronts[0].url)
    assert "shipyard_serving_completed_requests_total" in front_text
    assert 'shipyard_serving_ttft_ms{quantile="0.50"}' in front_text
    router_text = scrape(router.url)
    assert "shipyard_router_healthy_replicas 2" in router_text
    assert "shipyard_router_dispatched_total 1" in router_text
    assert ('shipyard_router_replica_healthy{replica="'
            + fronts[0].url + '"} 1') in router_text
    # Every line is NAME{labels} VALUE or NAME VALUE (parseable).
    for line in router_text.strip().splitlines():
        name, value = line.rsplit(" ", 1)
        float(value)


def test_failover_window_rejects_duplicate_request_id(params):
    """ADVICE r5 (medium): between a connection-error dispatch and the
    retry's re-registration, the duplicate-id gate must STILL hold —
    the claim is demoted to the reserved sentinel, never popped. A
    concurrent same-id POST inside that exact window is rejected."""
    import socket

    from batch_shipyard_tpu.models.router import DuplicateRequestError

    front = _front(params)
    # A port that refuses connections (bound then closed).
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_url = f"http://127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    # Never start(): no probers run, replicas stay optimistic-healthy,
    # and dispatch() is exercised directly (it needs no HTTP thread).
    router = ServingRouter([dead_url, front.url],
                           health_interval=30.0)
    with router._lock:
        for r in router._replicas:
            if r.url == front.url:
                r.dispatched = 5  # tie-break: dead replica picked 1st
    observed = {}
    orig_mark = router._mark_unhealthy

    def duplicate_inside_window(replica, exc):
        # Runs after finish(retrying=True) and BEFORE the retry
        # iteration re-registers the owner — the historical window.
        try:
            router._claim("fo-dup")
            observed["window_open"] = True
        except DuplicateRequestError:
            observed["window_open"] = False
        orig_mark(replica, exc)

    router._mark_unhealthy = duplicate_inside_window
    try:
        code, payload = router.dispatch(
            {"request_id": "fo-dup", "prompt": [1, 2],
             "max_new_tokens": 2})
        assert code == 200
        assert payload["_replica"] == front.url
        # The dead replica WAS tried first (the window ran).
        assert observed.get("window_open") is False, observed
        # After completion the id is released for reuse.
        code, _payload = router.dispatch(
            {"request_id": "fo-dup", "prompt": [2],
             "max_new_tokens": 1})
        assert code == 200
    finally:
        front.shutdown()


def test_two_racing_posts_across_forced_failover(params):
    """ADVICE r5 closure proof, adversarial form: TWO genuinely
    concurrent dispatches of the SAME request_id race while the
    router is mid-failover (dead replica tried first). Exactly one
    may decode; the other must be rejected by the duplicate gate —
    and the single surviving replica must have served exactly one
    request with that id. A real second thread (not just a probe
    inside the window) pins the whole claim/reserve/failover
    interleaving."""
    import socket

    from batch_shipyard_tpu.models.router import DuplicateRequestError

    front = _front(params)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_url = f"http://127.0.0.1:{probe.getsockname()[1]}"
    probe.close()
    router = ServingRouter([dead_url, front.url],
                           health_interval=30.0)
    with router._lock:
        for r in router._replicas:
            if r.url == front.url:
                r.dispatched = 5  # tie-break: dead replica first
    results: dict = {"ok": 0, "dup": 0, "other": []}
    results_lock = threading.Lock()
    # Deterministic interleaving: racer B fires the moment racer A
    # enters the failover window (after finish(retrying=True), before
    # the retry re-registers) — the historical double-decode window.
    window_entered = threading.Event()
    second_done = threading.Event()
    orig_mark = router._mark_unhealthy

    def mark_and_hold(replica, exc):
        orig_mark(replica, exc)
        window_entered.set()
        second_done.wait(timeout=30)  # keep A inside the window

    router._mark_unhealthy = mark_and_hold

    def racer(wait_for_window):
        if wait_for_window:
            window_entered.wait(timeout=30)
        try:
            code, payload = router.dispatch(
                {"request_id": "race-1", "prompt": [1, 2],
                 "max_new_tokens": 2})
            with results_lock:
                if code == 200:
                    results["ok"] += 1
                else:
                    results["other"].append((code, payload))
        except DuplicateRequestError:
            with results_lock:
                results["dup"] += 1
        except Exception as exc:  # noqa: BLE001 - recorded, asserted
            with results_lock:
                results["other"].append(repr(exc))
        finally:
            if wait_for_window:
                second_done.set()

    threads = [threading.Thread(target=racer, args=(False,)),
               threading.Thread(target=racer, args=(True,))]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert results["ok"] == 1, results
        assert results["dup"] == 1, results
        assert not results["other"], results
        # The fleet decoded the id exactly once.
        with urllib.request.urlopen(f"{front.url}/v1/stats",
                                    timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats.get("completed_requests") == 1, stats
        # The id is released after completion: a THIRD post reuses it.
        code, _ = router.dispatch(
            {"request_id": "race-1", "prompt": [3],
             "max_new_tokens": 1})
        assert code == 200
    finally:
        front.shutdown()


def test_router_midstream_timeout_orphans_ownership(params):
    """ADVICE r5 (medium): a mid-stream read timeout means the run may
    still be live on the (slow) replica — ownership must survive into
    orphan reconciliation, keeping the duplicate gate shut, instead of
    being popped by finish(ok=False)."""
    engine = serving.ContinuousBatcher(CFG, params, num_slots=2,
                                       max_decode_len=64)
    orig_step = engine.step
    engine.step = lambda: (time.sleep(1.0), orig_step())[1]
    front = ServingFrontEnd(engine, port=0).start()
    router = ServingRouter([front.url], health_interval=0.2,
                           request_timeout=0.5).start()
    try:
        req = urllib.request.Request(
            f"{router.url}/v1/generate",
            data=json.dumps({"request_id": "slow-stream",
                             "prompt": [3, 3], "max_new_tokens": 8,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            lines = [json.loads(line) for line in resp
                     if line.strip()]
        # The router terminated the client stream with an error line.
        assert any("error" in ln for ln in lines), lines
        # Ownership survived the timeout: the id is orphaned, not
        # released, and a retry is refused while the run may be live.
        assert "slow-stream" in router._owner
        assert "slow-stream" in router._orphaned
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(router.url, {"request_id": "slow-stream",
                               "prompt": [1], "max_new_tokens": 1})
        assert exc.value.code == 400
        # Once the replica forgets the run, reconciliation releases.
        front.cancel("slow-stream")
        assert _poll(lambda: "slow-stream" not in router._owner,
                     deadline_s=60.0)
        assert "slow-stream" not in router._orphaned
    finally:
        router.shutdown()
        front.shutdown()


def test_owner_ttl_retires_stale_entries_resubmit_safe(fleet):
    """TTL retirement of finished/leaked ownership entries: a stale
    RESERVED claim retires unconditionally, a stale LIVE entry retires
    once the owning replica provably forgot the id (404 probe) — and a
    retired id is immediately safe to resubmit (the regression the
    sweep must not introduce: dropping an id reopens the duplicate
    gate cleanly, without double-decode)."""
    router, fronts = fleet
    past = time.time() - 10_000
    with router._lock:
        replica = next(r for r in router._replicas
                       if r.url == fronts[0].url)
        router._owner["stale-reserved"] = None
        router._owner_stamp["stale-reserved"] = past
        router._owner["stale-live"] = replica
        router._owner_stamp["stale-live"] = past
    router._retire_stale()
    assert "stale-reserved" not in router._owner
    # The replica never knew "stale-live": the probe 404s, so the
    # leaked mapping is dropped too.
    assert "stale-live" not in router._owner
    assert not router._owner_stamp
    for rid in ("stale-reserved", "stale-live"):
        out = _post(router.url, {"request_id": rid, "prompt": [1, 2],
                                 "max_new_tokens": 2})
        assert out["num_tokens"] == 2


def test_owner_ttl_spares_live_decode(fleet):
    """The PR 10 failover-race guarantee survives any TTL: an id the
    owning replica still knows (a genuinely long decode) is NOT
    retired — its stamp refreshes instead, so the duplicate gate and
    sticky cancel keep working."""
    router, fronts = fleet
    result = {}

    def _long():
        result["r"] = _post(router.url, {
            "request_id": "ttl-live", "prompt": [2, 2],
            "max_new_tokens": 40}, timeout=240)

    t = threading.Thread(target=_long, daemon=True)
    t.start()
    assert _poll(lambda: any(f.knows("ttl-live") for f in fronts))
    with router._lock:
        router._owner_stamp["ttl-live"] = time.time() - 10_000
    router._retire_stale()
    assert "ttl-live" in router._owner
    assert time.time() - router._owner_stamp["ttl-live"] < 100, \
        "stamp not refreshed after a live probe"
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(router.url, {"request_id": "ttl-live", "prompt": [1],
                           "max_new_tokens": 1})
    assert exc.value.code == 400  # gate still shut
    t.join(120)
    assert result["r"]["num_tokens"] == 40


def test_prefix_affinity_routes_to_same_replica(fleet):
    """Requests sharing a client prefix key land on the replica whose
    KV pool holds the prefix pages; derived keys hash the first-N
    prompt tokens; affinity entries are pure hints retired by TTL."""
    router, _fronts = fleet
    urls = set()
    for k in range(4):
        out = _post(router.url, {"prompt": [k, 1, 2],
                                 "prefix_key": "tmpl-A",
                                 "max_new_tokens": 2})
        urls.add(out["_replica"])
    assert len(urls) == 1, "affinity failed to stick"
    assert router.affinity_routed >= 3
    _status, stats = _get(router.url, "/v1/stats")
    assert stats["affinity_routed"] >= 3
    # Derived keys: identical heads agree, short prompts get none.
    head = list(range(32))
    k1 = router._affinity_key({"prompt": head + [99]})
    k2 = router._affinity_key({"prompt": head + [7, 8]})
    assert k1 is not None and k1 == k2
    assert router._affinity_key({"prompt": [5] * 31}) is None
    assert router._affinity_key(
        {"prefix_key": "x", "prompt": head}) == "client:x"
    # TTL drops affinity hints (no probe needed — they are not
    # correctness state).
    with router._lock:
        for key in list(router._affinity):
            router._affinity[key] = (router._affinity[key][0],
                                     time.time() - 10_000)
    router._retire_stale()
    assert not router._affinity


def test_prefix_affinity_yields_under_load_imbalance(fleet):
    """Stickiness must not create hot spots: when the sticky replica
    is more than affinity_load_slack ahead of the least-loaded one,
    the request routes away (and re-homes the prefix there)."""
    router, _fronts = fleet
    out = _post(router.url, {"prompt": [1, 2], "prefix_key": "hot",
                             "max_new_tokens": 1})
    sticky_url = out["_replica"]
    with router._lock:
        for r in router._replicas:
            if r.url == sticky_url:
                r.inflight += 10  # simulated hot spot
    try:
        out2 = _post(router.url, {"prompt": [3, 4],
                                  "prefix_key": "hot",
                                  "max_new_tokens": 1})
        assert out2["_replica"] != sticky_url
    finally:
        with router._lock:
            for r in router._replicas:
                if r.url == sticky_url:
                    r.inflight -= 10


def test_stalled_probe_does_not_delay_other_replica_detection(params):
    """ADVICE r5 (low): with long-lived per-replica probers, a hung
    probe on replica A must not stretch fault detection for replica B
    — the old per-interval thread sweep joined on the slowest probe
    (probe_timeout*2+1) before re-probing anyone."""
    from http.server import ThreadingHTTPServer

    from batch_shipyard_tpu.models.server import JsonRequestHandler

    stall = threading.Event()

    class StallableHandler(JsonRequestHandler):
        def do_GET(self):  # noqa: N802
            if stall.is_set():
                time.sleep(15)  # hang past the detection deadline
            if self.path == "/healthz":
                self._reply(200, {"ok": True})
            else:
                self._reply(200, {"engine_backlog": 0})

    stall_srv = ThreadingHTTPServer(("127.0.0.1", 0),
                                    StallableHandler)
    threading.Thread(target=stall_srv.serve_forever,
                     daemon=True).start()
    host, port = stall_srv.server_address[:2]
    front_b = _front(params)
    router = ServingRouter([f"http://{host}:{port}", front_b.url],
                           health_interval=0.2).start()
    try:
        assert _poll(lambda: router.healthy_count() == 2,
                     deadline_s=10.0)
        stall.set()
        time.sleep(0.5)  # let A's prober enter the hang
        front_b.shutdown()
        detected_at = time.monotonic()
        assert _poll(
            lambda: {s["url"]: s["healthy"]
                     for s in router.replicas()}[front_b.url] is False,
            deadline_s=3.0), \
            "replica B's failure not detected while A's probe hung"
        assert time.monotonic() - detected_at < 3.5
    finally:
        stall_srv.shutdown()
        stall_srv.server_close()
        router.shutdown()

"""Pool suspend/start, ssh user fan-out, diag logs, account info, and
workload checkpoint/resume tests."""

import json
import os
import time

import pytest

from batch_shipyard_tpu import fleet
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_ctx(tmp_path, pool_id="op"):
    creds = {"credentials": {"storage": {
        "backend": "localfs", "root": str(tmp_path / "store")}}}
    pool_conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-4"},
        "max_wait_time_seconds": 30}}
    ctx = fleet.load_context(extra={"credentials": creds,
                                    "pool": pool_conf})
    fleet.action_pool_add(ctx)
    return ctx


def test_pool_suspend_start(tmp_path):
    ctx = make_ctx(tmp_path)
    try:
        fleet.action_pool_suspend(ctx)
        nodes = pool_mgr.list_nodes(ctx.store, "op")
        assert all(n.state in ("suspended", "offline") for n in nodes)
        assert pool_mgr.get_pool(ctx.store, "op")[
            "state"] == "suspended"
        fleet.action_pool_start(ctx)
        nodes = pool_mgr.list_nodes(ctx.store, "op")
        assert all(n.state == "idle" for n in nodes)
        # Pool is functional again.
        jobs = settings_mod.job_settings_list({"job_specifications": [
            {"id": "after", "tasks": [{"command": "echo back"}]}]})
        jobs_mgr.add_jobs(ctx.store, ctx.pool, jobs)
        tasks = jobs_mgr.wait_for_tasks(ctx.store, "op", "after",
                                        timeout=30)
        assert tasks[0]["state"] == "completed"
    finally:
        ctx.substrate().stop_all()


def test_pool_user_add_del(tmp_path):
    pytest.importorskip(
        "cryptography",
        reason="ssh keypair generation needs the cryptography wheel")
    ctx = make_ctx(tmp_path)
    try:
        private_path, public_path = fleet.action_pool_user_add(
            ctx, "tester", str(tmp_path))
        assert os.path.exists(private_path)
        substrate = ctx.substrate()
        deadline = time.monotonic() + 10
        found = False
        while time.monotonic() < deadline and not found:
            for node in pool_mgr.list_nodes(ctx.store, "op"):
                agent = substrate.agent("op", node.node_id)
                if agent is None:
                    continue
                auth = os.path.join(agent.work_dir, "ssh", "tester",
                                    "authorized_keys")
                if os.path.exists(auth):
                    found = True
                    break
            time.sleep(0.1)
        assert found, "public key never landed on any node"
        fleet.action_pool_user_del(ctx, "tester")
    finally:
        ctx.substrate().stop_all()


def test_diag_logs_upload(tmp_path):
    ctx = make_ctx(tmp_path)
    try:
        count = fleet.action_diag_logs_upload(ctx)
        assert count == 1  # v5e-4 = 1 worker
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            keys = ctx.store.list_objects("nodelogs/op/")
            if keys:
                break
            time.sleep(0.1)
        assert any(k.endswith(".nodeprep_finished") for k in keys)
    finally:
        ctx.substrate().stop_all()


def test_account_info(tmp_path, capsys):
    ctx = make_ctx(tmp_path)
    try:
        fleet.action_account_info(ctx, raw=True)
        out = json.loads(capsys.readouterr().out)
        assert out["storage_backend"] == "localfs"
        assert "op" in out["pools"]
    finally:
        ctx.substrate().stop_all()


def test_workload_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp
    import numpy as np
    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    from batch_shipyard_tpu.parallel import train as train_mod
    from batch_shipyard_tpu.workloads import checkpoint

    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    config = train_mod.make_transformer_config(
        mesh, vocab_size=128, d_model=32, n_layers=1, n_heads=2,
        d_head=16, d_ff=64, max_seq_len=32, dtype=jnp.float32,
        param_dtype=jnp.float32)
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=8, seq_len=32)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, 128, (8, 32)), jnp.int32),
        "targets": jnp.asarray(rng.randint(0, 128, (8, 32)),
                               jnp.int32)}
    params, opt_state, _ = harness.step(harness.params,
                                        harness.opt_state, batch)
    ckpt_dir = str(tmp_path / "ckpt")
    checkpoint.save(ckpt_dir, 1, params, opt_state)
    assert checkpoint.latest_step(ckpt_dir) == 1
    restored = checkpoint.restore(ckpt_dir, params, opt_state)
    assert restored is not None
    r_params, _r_opt, step = restored
    assert step == 1
    leaf = jax.tree_util.tree_leaves(r_params)[0]
    orig = jax.tree_util.tree_leaves(params)[0]
    np.testing.assert_allclose(np.asarray(leaf), np.asarray(orig))
    assert checkpoint.restore(str(tmp_path / "empty"), params,
                              opt_state) is None


import jax  # noqa: E402

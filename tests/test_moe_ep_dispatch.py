"""Explicit expert-parallel MoE dispatch over the hierarchical
all-to-all (ROADMAP 'shard_map MoE dispatch variant'): equivalence
with the dense einsum formulation on a factored 2x4 ep mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.utils.compat import shard_map

from batch_shipyard_tpu.models import moe

E, D, F = 8, 64, 128          # experts, d_model, d_ff
G_LOCAL = 16                  # tokens per device group
CAP = 4


def _mesh():
    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devices, ("ep_out", "ep_in"))


def _weights(seed=0):
    rng = np.random.RandomState(seed)
    return (
        jnp.asarray(rng.randn(D, E) / 8, jnp.float32),       # router
        jnp.asarray(rng.randn(E, D, F) / 8, jnp.float32),    # gate
        jnp.asarray(rng.randn(E, D, F) / 8, jnp.float32),    # up
        jnp.asarray(rng.randn(E, F, D) / 11, jnp.float32),   # down
    )


def _dense_group(flat_g, router, w_gate, w_up, w_down, routing,
                 num_selected=2):
    """The einsum formulation on ONE device group with FULL expert
    weights — the oracle for the distributed exchange."""
    logits = flat_g.astype(jnp.float32) @ router
    if routing == "expert_choice":
        dispatch, combine, aux = moe.expert_choice_routing(logits, CAP)
    elif routing == "topk":
        dispatch, combine, aux = moe.topk_routing(logits, CAP,
                                                  num_selected)
    else:
        dispatch, combine, aux = moe.top1_routing(logits, CAP)
    expert_in = jnp.einsum("gec,gd->ecd", dispatch, flat_g)
    gate_act = jnp.einsum("ecd,edf->ecf", expert_in, w_gate)
    up_act = jnp.einsum("ecd,edf->ecf", expert_in, w_up)
    out = jnp.einsum("ecf,efd->ecd", nn.silu(gate_act) * up_act,
                     w_down)
    return jnp.einsum("gec,ecd->gd", combine, out), aux


@pytest.mark.parametrize("routing", ["top1", "topk",
                                     "expert_choice"])
def test_hierarchical_ep_dispatch_matches_dense(routing):
    mesh = _mesh()
    router, w_gate, w_up, w_down = _weights()
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)

    def body(flat, router, wg, wu, wd):
        return moe.moe_ep_apply_shard(
            flat, router, wg, wu, wd, capacity=CAP,
            outer_axis="ep_out", inner_axis="ep_in",
            routing=routing, dtype=jnp.float32)

    ep = ("ep_out", "ep_in")
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(ep, None), P(None, None), P(ep, None, None),
                  P(ep, None, None), P(ep, None, None)),
        out_specs=(P(ep, None), P()),
        check_vma=False)
    got, aux = jax.jit(fn)(tokens, router, w_gate, w_up, w_down)

    want = []
    want_aux = []
    for g in range(8):
        y, a = _dense_group(tokens[g * G_LOCAL:(g + 1) * G_LOCAL],
                            router, w_gate, w_up, w_down, routing)
        want.append(y)
        want_aux.append(a)
    want = jnp.concatenate(want, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux),
                               float(np.mean(want_aux)), rtol=1e-5)


def test_hierarchical_ep_dispatch_differentiable():
    """The exchange is an involution of transposable collectives, so
    the whole body must be trainable end to end."""
    mesh = _mesh()
    router, w_gate, w_up, w_down = _weights(seed=5)
    rng = np.random.RandomState(7)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)
    ep = ("ep_out", "ep_in")

    def loss(params, flat):
        def body(flat, router, wg, wu, wd):
            y, aux = moe.moe_ep_apply_shard(
                flat, router, wg, wu, wd, capacity=CAP,
                outer_axis="ep_out", inner_axis="ep_in",
                dtype=jnp.float32)
            return jnp.sum(y ** 2)[None] + 0.01 * aux[None]

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P(ep, None), P(None, None),
                      P(ep, None, None), P(ep, None, None),
                      P(ep, None, None)),
            out_specs=P(ep),
            check_vma=False)
        return jnp.sum(fn(flat, *params))

    grads = jax.jit(jax.grad(loss))((router, w_gate, w_up, w_down),
                                    tokens)
    for g in grads:
        arr = np.asarray(g)
        assert np.all(np.isfinite(arr))
        assert np.abs(arr).sum() > 0

def test_single_axis_ep_dispatch_matches_dense():
    """outer_axis=None: the exchange degenerates to one all_to_all
    over a single 8-way ep axis — same per-group outputs."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
    router, w_gate, w_up, w_down = _weights(seed=11)
    rng = np.random.RandomState(13)
    tokens = jnp.asarray(rng.randn(8 * G_LOCAL, D), jnp.float32)

    def body(flat, router, wg, wu, wd):
        return moe.moe_ep_apply_shard(
            flat, router, wg, wu, wd, capacity=CAP,
            outer_axis=None, inner_axis="ep", dtype=jnp.float32)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P("ep", None), P(None, None), P("ep", None, None),
                  P("ep", None, None), P("ep", None, None)),
        out_specs=(P("ep", None), P()),
        check_vma=False)
    got, aux = jax.jit(fn)(tokens, router, w_gate, w_up, w_down)
    outs, auxes = zip(*[
        _dense_group(tokens[g * G_LOCAL:(g + 1) * G_LOCAL],
                     router, w_gate, w_up, w_down, "top1")
        for g in range(8)])
    want = jnp.concatenate(outs, axis=0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(np.mean(auxes)),
                               rtol=1e-5)


@pytest.mark.slow
def test_moe_stage_inside_1f1b_pipeline():
    """dp x pp x ep composition (ROADMAP 'wire it into the training
    path'): a 2-stage 1F1B pipeline whose stages each run an
    expert-parallel MoE over the ep axis — loss AND parameter
    gradients match the sequential dense computation."""
    from batch_shipyard_tpu.parallel import pipeline as pl

    S, N_EP, B, M = 2, 4, 32, 2
    mb = B // M                      # tokens per microbatch
    g_local = mb // N_EP
    cap = max(1, g_local)            # per-group capacity
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(S, N_EP),
                ("pp", "ep"))
    rng = np.random.RandomState(17)

    def stage_params(seed):
        r = np.random.RandomState(seed)
        return {
            "router": jnp.asarray(r.randn(D, E) / 8, jnp.float32),
            "wg": jnp.asarray(r.randn(E, D, F) / 8, jnp.float32),
            "wu": jnp.asarray(r.randn(E, D, F) / 8, jnp.float32),
            "wd": jnp.asarray(r.randn(E, F, D) / 11, jnp.float32),
        }

    per_stage = [stage_params(1), stage_params(2)]
    stacked = pl.stack_stage_params(per_stage)
    x = jnp.asarray(rng.randn(B, D), jnp.float32)
    targets = jnp.asarray(rng.randn(B, D), jnp.float32)
    last = {"w": jnp.asarray(rng.randn(D, D) / 8, jnp.float32)}

    def stage_fn(p, xin):
        y, _aux = moe.moe_ep_stage(
            xin, p["router"], p["wg"], p["wu"], p["wd"],
            capacity=cap, inner_axis="ep", dtype=jnp.float32)
        return xin + y  # residual, like a transformer block

    def last_fn(lp, y, tgt):
        return jnp.mean((y @ lp["w"] - tgt) ** 2)

    specs = {
        "router": P("pp", None, None),
        "wg": P("pp", "ep", None, None),
        "wu": P("pp", "ep", None, None),
        "wd": P("pp", "ep", None, None),
    }
    loss, dstage, dlast, _dx = pl.pipeline_1f1b_train(
        stacked, x, targets, last, mesh=mesh, stage_fn=stage_fn,
        last_fn=last_fn, num_microbatches=M, batch_axes=(),
        stage_param_specs=specs)

    # Sequential dense reference with the SAME routing groups: each
    # microbatch's tokens split into N_EP groups routed
    # independently with full expert weights.
    def dense_stage(p, xin):
        outs = []
        for g in range(N_EP):
            seg = xin[g * g_local:(g + 1) * g_local]
            logits = seg.astype(jnp.float32) @ p["router"]
            d_, c_, _a = moe.top1_routing(logits, cap)
            ein = jnp.einsum("gec,gd->ecd", d_, seg)
            ga = jnp.einsum("ecd,edf->ecf", ein, p["wg"])
            ua = jnp.einsum("ecd,edf->ecf", ein, p["wu"])
            eo = jnp.einsum("ecf,efd->ecd", nn.silu(ga) * ua,
                            p["wd"])
            outs.append(jnp.einsum("gec,ecd->gd", c_, eo))
        return xin + jnp.concatenate(outs, axis=0)

    def ref_loss(stages, lastp, x):
        total = 0.0
        for m in range(M):
            h = x[m * mb:(m + 1) * mb]
            tgt = targets[m * mb:(m + 1) * mb]
            for p in stages:
                h = dense_stage(p, h)
            total = total + last_fn(lastp, h, tgt)
        return total / M

    want = ref_loss(per_stage, last, x)
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-5)
    g_want = jax.grad(
        lambda stages, lastp: ref_loss(stages, lastp, x),
        argnums=(0, 1))(per_stage, last)
    for k in ("router", "wg", "wu", "wd"):
        got = np.asarray(dstage[k])          # [S, ...]
        ref0 = np.asarray(g_want[0][0][k])
        ref1 = np.asarray(g_want[0][1][k])
        np.testing.assert_allclose(got[0], ref0, rtol=3e-4,
                                   atol=3e-5)
        np.testing.assert_allclose(got[1], ref1, rtol=3e-4,
                                   atol=3e-5)
    np.testing.assert_allclose(np.asarray(dlast["w"]),
                               np.asarray(g_want[1]["w"]),
                               rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("axes", [("ep",), ("ep_out", "ep_in")])
def test_moe_ep_stage_grads_including_aux(axes):
    """moe_ep_stage on a replicated stream: loss = f(y) + c*aux must
    match the dense reference's gradients EXACTLY (the aux path is
    where a VJP miscount hides — it was n_ep-times overcounted before
    this test existed), on both the single-axis and factored-mesh
    forms."""
    if len(axes) == 1:
        mesh = Mesh(np.array(jax.devices()[:8]), axes)
        outer, inner = None, axes[0]
    else:
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), axes)
        outer, inner = axes
    router, w_gate, w_up, w_down = _weights(seed=21)
    rng = np.random.RandomState(23)
    n_ep = 8
    tokens = jnp.asarray(rng.randn(n_ep * G_LOCAL, D), jnp.float32)
    spec_e = P(axes if len(axes) > 1 else axes[0], None, None)

    # moe_ep_stage's contract is the pipeline's: differentiation
    # happens INSIDE the shard_map body (manual vjp per device, like
    # pipeline_1f1b_train's tick), where the replicated-full
    # cotangent invariant holds by construction. Replicating that
    # here: grads computed in-body, shipped out with their natural
    # specs (router replicated, experts ep-sharded).
    def body(flat, r, a, b, c):
        def local_loss(r, a, b, c):
            y, aux = moe.moe_ep_stage(
                flat, r, a, b, c, capacity=CAP, inner_axis=inner,
                outer_axis=outer, dtype=jnp.float32)
            return jnp.sum(y ** 2) + 0.3 * aux

        return jax.grad(local_loss, argnums=(0, 1, 2, 3))(r, a, b, c)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, None), spec_e, spec_e, spec_e),
        out_specs=(P(None, None), spec_e, spec_e, spec_e),
        check_vma=False)
    got = jax.jit(fn)(tokens, router, w_gate, w_up, w_down)

    def dense_loss(params, flat):
        r, wg, wu, wd = params
        total = 0.0
        auxes = []
        for g in range(n_ep):
            seg = flat[g * G_LOCAL:(g + 1) * G_LOCAL]
            logits = seg.astype(jnp.float32) @ r
            d_, c_, a_ = moe.top1_routing(logits, CAP)
            ein = jnp.einsum("gec,gd->ecd", d_, seg)
            ga = jnp.einsum("ecd,edf->ecf", ein, wg)
            ua = jnp.einsum("ecd,edf->ecf", ein, wu)
            eo = jnp.einsum("ecf,efd->ecd", nn.silu(ga) * ua, wd)
            total = total + jnp.sum(
                jnp.einsum("gec,ecd->gd", c_, eo) ** 2)
            auxes.append(a_)
        return total + 0.3 * jnp.mean(jnp.stack(auxes))

    want = jax.grad(dense_loss)((router, w_gate, w_up, w_down),
                                tokens)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-6)


def test_moe_ep_stage_rejects_indivisible_tokens():
    mesh = Mesh(np.array(jax.devices()[:8]), ("ep",))
    router, w_gate, w_up, w_down = _weights()
    tokens = jnp.zeros((30, D), jnp.float32)  # 30 % 8 != 0

    def body(flat, r, a, b, c):
        return moe.moe_ep_stage(flat, r, a, b, c, capacity=CAP,
                                inner_axis="ep",
                                dtype=jnp.float32)[0]

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, None), P("ep", None, None),
                  P("ep", None, None), P("ep", None, None)),
        out_specs=P(), check_vma=False)
    with pytest.raises(ValueError) as exc:
        jax.jit(fn)(tokens, router, w_gate, w_up, w_down)
    assert "not divisible" in str(exc.value)

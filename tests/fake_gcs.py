"""A minimal in-memory fake of the google.cloud.storage surface that
state/gcs.py uses, with faithful generation-precondition semantics —
lets the full state-store contract suite execute the real GCSStateStore
logic (lease steal via matched-generation swap, claim races) without a
network or credentials."""

from __future__ import annotations

import datetime
import threading


class PreconditionFailed(Exception):
    pass


class NotFound(Exception):
    pass


class FakeExceptionsModule:
    PreconditionFailed = PreconditionFailed
    NotFound = NotFound


class _Store:
    def __init__(self):
        self.lock = threading.RLock()
        # name -> (bytes, generation)
        self.blobs: dict[str, tuple[bytes, int]] = {}
        self.counter = 0


class FakeBlob:
    def __init__(self, store: _Store, name: str):
        self._store = store
        self.name = name
        self.generation = None
        self.size = None
        self.updated = None

    def upload_from_string(self, data, if_generation_match=None):
        if isinstance(data, str):
            data = data.encode()
        with self._store.lock:
            current = self._store.blobs.get(self.name)
            if if_generation_match is not None:
                cur_gen = current[1] if current else 0
                if cur_gen != if_generation_match:
                    raise PreconditionFailed(self.name)
            self._store.counter += 1
            self._store.blobs[self.name] = (bytes(data),
                                            self._store.counter)
            self.generation = self._store.counter
            self.size = len(data)
            self.updated = datetime.datetime.now(datetime.timezone.utc)

    def download_as_bytes(self, start=None, end=None):
        with self._store.lock:
            if self.name not in self._store.blobs:
                raise NotFound(self.name)
            data = self._store.blobs[self.name][0]
        if start is not None:
            # GCS ranges are inclusive of end.
            return data[start:(end + 1) if end is not None else None]
        return data

    def upload_from_file(self, fileobj, if_generation_match=None):
        self.upload_from_string(fileobj.read(),
                                if_generation_match=if_generation_match)

    def reload(self):
        with self._store.lock:
            if self.name not in self._store.blobs:
                raise NotFound(self.name)
            data, gen = self._store.blobs[self.name]
            self.generation = gen
            self.size = len(data)
            self.updated = datetime.datetime.now(
                datetime.timezone.utc)

    def generate_signed_url(self, version="v4", method="GET",
                            expiration=None):
        # Deterministic fake: enough for the store-level contract
        # (URL embeds blob, method and expiry seconds).
        secs = int(expiration.total_seconds()) if expiration else 0
        return (f"https://storage.googleapis.example/{self.name}"
                f"?X-Goog-Method={method}&X-Goog-Expires={secs}"
                f"&X-Goog-Signature=fake")

    def delete(self, if_generation_match=None):
        with self._store.lock:
            if self.name not in self._store.blobs:
                raise NotFound(self.name)
            if if_generation_match is not None and (
                    self._store.blobs[self.name][1] !=
                    if_generation_match):
                raise PreconditionFailed(self.name)
            del self._store.blobs[self.name]


class FakeBucket:
    def __init__(self, store: _Store):
        self._store = store

    def blob(self, name: str) -> FakeBlob:
        return FakeBlob(self._store, name)


class FakeClient:
    def __init__(self):
        self._store = _Store()
        self._bucket = FakeBucket(self._store)

    def bucket(self, _name: str) -> FakeBucket:
        return self._bucket

    def list_blobs(self, _bucket, prefix: str = ""):
        # Metadata snapshot under the lock, like a real GCS listing:
        # iteration never raises for blobs deleted concurrently.
        with self._store.lock:
            snapshot = sorted(
                (name, data, gen)
                for name, (data, gen) in self._store.blobs.items()
                if name.startswith(prefix))
        for name, data, gen in snapshot:
            blob = self._bucket.blob(name)
            blob.generation = gen
            blob.size = len(data)
            blob.updated = datetime.datetime.now(
                datetime.timezone.utc)
            yield blob


def make_fake_gcs_store(prefix: str = "t"):
    """Construct a real GCSStateStore (through its real constructor)
    wired to the fake client."""
    from batch_shipyard_tpu.state.gcs import GCSStateStore
    return GCSStateStore("fake", prefix=prefix, client=FakeClient(),
                         exceptions_module=FakeExceptionsModule)

"""Static consistency: every state-store table the package touches is
declared in state/names.py — a new table (e.g. TABLE_GOODPUT) cannot
be typo-forked into a parallel name nobody reads.

Pure AST scan over batch_shipyard_tpu/**/*.py; cheap by design (no
imports of the scanned modules, no JAX)."""

import ast
import pathlib

from batch_shipyard_tpu.state import names

PACKAGE = pathlib.Path(names.__file__).resolve().parent.parent

# StateStore methods whose first argument is a table name.
_TABLE_METHODS = {
    "insert_entity", "upsert_entity", "merge_entity", "get_entity",
    "query_entities", "delete_entity", "insert_entities",
}

_DECLARED_ATTRS = {attr for attr in dir(names)
                   if attr.startswith("TABLE_")}
_DECLARED_VALUES = {getattr(names, attr) for attr in _DECLARED_ATTRS}


def _iter_package_sources():
    for path in sorted(PACKAGE.rglob("*.py")):
        yield path, ast.parse(path.read_text(encoding="utf-8"),
                              filename=str(path))


def test_declared_table_values_are_unique():
    assert len(_DECLARED_VALUES) == len(_DECLARED_ATTRS), (
        "two TABLE_* constants in state/names.py share a value")


def test_every_table_literal_is_declared():
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            # Any TABLE_* attribute/name reference must resolve to a
            # declared constant.
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("TABLE_"):
                if node.attr not in _DECLARED_ATTRS:
                    problems.append(
                        f"{rel}:{node.lineno}: undeclared "
                        f"{node.attr}")
            # A string literal passed as the table argument of a
            # store call must be a declared table VALUE.
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _TABLE_METHODS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str):
                    if first.value not in _DECLARED_VALUES:
                        problems.append(
                            f"{rel}:{node.lineno}: table literal "
                            f"{first.value!r} not declared in "
                            f"state/names.py")
    assert not problems, "\n".join(problems)


def test_goodput_table_declared():
    # The event log's table rides the same registry as every other
    # coordination surface.
    assert names.TABLE_GOODPUT == "goodput"
    assert "TABLE_GOODPUT" in _DECLARED_ATTRS


def test_goodput_program_constants_are_declared():
    """Every PROGRAM_* constant referenced at an emit site resolves
    to a declared constant in goodput/events.py whose value is a
    registered EVENT_KIND — a typo'd phase name cannot silently
    produce events the accounting drops."""
    from batch_shipyard_tpu.goodput import events as gp_events
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("PROGRAM_"):
                value = getattr(gp_events, node.attr, None)
                if value is None:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} not "
                        f"declared in goodput/events.py")
                elif value not in gp_events.EVENT_KINDS:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} value "
                        f"{value!r} missing from EVENT_KINDS")
    assert not problems, "\n".join(problems)


def test_train_workloads_enable_the_compile_cache():
    """Every workload that builds a parallel.train harness must go
    through the compilecache enable hook (compilecache.
    enable_from_args) AND register its flag surface
    (add_compile_cache_args) — a workload that silently opts out of
    the persistent cache pays a cold XLA compile on every node and
    every restart, exactly the badput the warm-start pipeline exists
    to remove (mirrors the no-blocking-checkpoint-save check)."""
    problems = []
    for path in sorted((PACKAGE / "workloads").glob("train_*.py")):
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
        rel = path.relative_to(PACKAGE.parent)
        uses_train = any(
            isinstance(node, ast.ImportFrom) and
            node.module == "batch_shipyard_tpu.parallel" and
            any(alias.name == "train" for alias in node.names)
            for node in ast.walk(tree))
        if not uses_train:
            continue
        calls = {
            node.func.attr
            for node in ast.walk(tree)
            if isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute)}
        for required in ("enable_from_args",
                         "add_compile_cache_args"):
            if required not in calls:
                problems.append(
                    f"{rel}: parallel.train workload never calls "
                    f"compilecache.{required} — it silently opts "
                    f"out of the persistent compile cache")
    assert not problems, "\n".join(problems)


def test_train_loops_never_call_blocking_checkpoint_save():
    """The train workloads must drive checkpoints through
    checkpoint.TrainCheckpointer (which routes to the async manager
    under --async-checkpoint): a direct blocking ``checkpoint.save``
    in a step loop reintroduces the full-persist stall the zero-stall
    pipeline exists to remove, and skips the stale-step guard."""
    problems = []
    for path in sorted((PACKAGE / "workloads").glob("train_*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "save" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "checkpoint":
                problems.append(
                    f"{rel}:{node.lineno}: direct blocking "
                    f"checkpoint.save() in a train workload — use "
                    f"checkpoint.TrainCheckpointer")
    assert not problems, "\n".join(problems)

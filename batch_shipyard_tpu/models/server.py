"""HTTP serving front end over the continuous-batching engine.

The reference has no serving story; SURVEY.md treats recipes as the
acceptance surface, and an Orca/vLLM-class engine is judged by
TTFT/TPOT under load — which needs an ingress path. This front end is
deliberately stdlib-only (http.server): the engine's throughput comes
from the jitted decode step, not the socket layer, and one thread per
in-flight request is plenty for a per-replica slot count.

Architecture:
  - HTTP handlers parse/validate and enqueue (request, Event) pairs;
  - ONE engine thread owns the ContinuousBatcher: it drains the
    submission queue, calls engine.step() while work is active, and
    completes waiters — the engine is never touched from two threads;
  - the engine's on_token hook timestamps each request's first token,
    giving true TTFT (time-to-first-token) rather than
    time-to-completion.

Endpoints:
  POST /v1/generate   {"prompt": [ids], "max_new_tokens": n,
                       "request_id"?: str, "eos_id"?: int,
                       "stream"?: bool}
      -> {"request_id", "tokens", "num_tokens", "ttft_ms",
          "tpot_ms", "latency_ms"}
      With "stream": true the response is newline-delimited JSON
      (chunked transfer): one {"token": t, "index": i} line per
      generated token as it decodes, then a final line with the full
      result object — the client observes TTFT directly.
  DELETE /v1/requests/<request_id>   abort a queued/decoding request
      (202 accepted; the waiter completes with a 'cancelled' error;
      404 for ids this front end does not currently own — a fleet
      router's broadcast cancel probes replicas by that signal)
  GET  /v1/stats      aggregate counters + latency percentiles
  GET  /healthz       liveness
"""

from __future__ import annotations

import json
import math
import queue
import socket
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from batch_shipyard_tpu.models.serving import ContinuousBatcher, Request
from batch_shipyard_tpu.trace import spans as trace_spans
from batch_shipyard_tpu.trace.histogram import LatencyHistogram
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class RequestCancelled(Exception):
    """The request was aborted via the cancel API."""


class RequestShed(Exception):
    """The engine dropped the request under overload (its TTFT
    deadline was blown past the shed grace) — surfaced as 503 so
    clients/routers treat it as back-pressure, not failure."""


class RequestDraining(Exception):
    """This replica is draining (preempt/evict notice): the request
    was refused, or its decode was abandoned at the grace deadline.
    Surfaced as 503 + Retry-After with a "draining" marker so the
    router fails over (and, mid-stream, resumes on a sibling) instead
    of treating the replica as failed."""


class TooManyRequests(Exception):
    """Front-door concurrency cap exceeded — 429 back-pressure; the
    router backs off and retries a sibling."""


class CompletedReplay(Exception):
    """A resume landed for a request this replica already finished:
    serve the cached result instead of decoding again (exactly-once
    across a router failover that raced completion)."""

    def __init__(self, result: dict) -> None:
        super().__init__(result["request_id"])
        self.result = result


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Shared handler base for the serving HTTP surfaces (this front
    end and models/router.py): HTTP/1.1 (required for chunked
    streaming; all non-streaming replies carry Content-Length so
    keep-alive is safe), silenced per-request logging, and the JSON
    reply helper."""

    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: N802
        pass

    def _reply(self, code: int, payload: dict,
               headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(body)

    def _delete_request_id(self) -> Optional[str]:
        """Parse /v1/requests/<id> from a DELETE path; None (and a
        404 reply) otherwise."""
        prefix = "/v1/requests/"
        if not self.path.startswith(prefix):
            self._reply(404, {"error": "not found"})
            return None
        return self.path[len(prefix):]

    def _reply_metrics(self, lines: list[str]) -> None:
        """Prometheus text exposition (the monitoring stack's scrape
        format — docs/09-monitoring.md)."""
        body = ("\n".join(lines) + "\n").encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _escape_label(value) -> str:
    """Prometheus exposition label escaping (\\, \", newline) — one
    odd replica URL must not invalidate the whole scrape."""
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def prometheus_lines(prefix: str, values: dict,
                     labels: Optional[dict] = None) -> list[str]:
    """Render {name: number} as Prometheus gauges with optional
    labels; None values are skipped (absent metric, not zero).
    Values render at full float64 precision — ':g' would quantize
    counters past 1e6 and break rate()/increase()."""
    label_str = ""
    if labels:
        inner = ",".join(
            f'{k}="{_escape_label(v)}"'
            for k, v in sorted(labels.items()))
        label_str = "{" + inner + "}"
    out = []
    for name, value in values.items():
        if value is None:
            continue
        out.append(f"{prefix}_{name}{label_str} "
                   f"{float(value):.17g}")
    return out


class _Pending:
    __slots__ = ("request", "event", "submitted_at", "submitted_wall",
                 "admitted_at", "first_token_at",
                 "finished_at", "tokens", "error", "token_queue",
                 "cancelled", "shed", "draining", "resumed",
                 "emitted")

    def __init__(self, request: Request, stream: bool = False,
                 resumed: Optional[list[int]] = None) -> None:
        self.request = request
        self.event = threading.Event()
        self.submitted_at = time.perf_counter()
        # Wall-clock arrival: the anchor the request's trace spans
        # are placed at (perf_counter deltas give the durations).
        self.submitted_wall = time.time()
        # Slot admission (the engine's on_admit hook): the
        # queued -> prefill boundary.
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.tokens: Optional[list[int]] = None
        self.error: Optional[str] = None
        self.cancelled = False
        self.shed = False
        # Drain: the replica abandoned/refused this request while
        # shutting down — the waiter surfaces RequestDraining and the
        # router resumes elsewhere.
        self.draining = False
        # Router recovery: tokens a prior replica already emitted
        # (the engine re-prefills them; on_token indexes continue
        # globally from len(resumed)).
        self.resumed: Optional[list[int]] = resumed
        # Highest emitted-token count (global index + 1): the
        # /v1/requests/<id> phase probe's progress source of truth.
        self.emitted = len(resumed) if resumed else 0
        # Streaming mode: the engine thread feeds (index, token)
        # pairs here as they decode; None terminates the stream.
        self.token_queue: Optional["queue.Queue"] = (
            queue.Queue() if stream else None)


def percentile(values: list[float], pct: float) -> float:
    """Nearest-rank percentile (no numpy dependency in the serving
    path)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    k = max(1, min(len(ordered),
                   math.ceil(pct / 100.0 * len(ordered))))
    return ordered[k - 1]


class ServingFrontEnd:
    """Owns the engine thread + HTTP server around a
    ContinuousBatcher."""

    def __init__(self, engine: ContinuousBatcher,
                 host: str = "127.0.0.1", port: int = 0,
                 slo_classes: Optional[dict] = None,
                 max_inflight: Optional[int] = None,
                 io_timeout_s: Optional[float] = None,
                 drain_grace_s: float = 30.0) -> None:
        """slo_classes maps class name ->
        {"ttft_ms": float|None, "tpot_ms": float|None}
        (config/settings.ServingSloSettings.class_targets()). A
        request's "slo_class" resolves to those targets at admission;
        explicit "ttft_target_ms"/"tpot_target_ms" in the request
        body override its class. With no classes configured, class
        names pass through untargeted.

        Front-door hardening: max_inflight caps accepted-but-
        unfinished requests (excess gets 429 back-pressure; resumes
        are exempt — a recovery must not bounce), io_timeout_s sets a
        per-connection socket read/write deadline so one wedged
        client cannot pin a handler thread forever, drain_grace_s is
        the default budget drain() gives in-flight decodes before
        abandoning them."""
        self.engine = engine
        self.slo_classes = dict(slo_classes or {})
        self.max_inflight = max_inflight
        self.drain_grace_s = drain_grace_s
        # Drain ladder state: _draining flips once (preempt/evict
        # notice or explicit drain()); handlers refuse new work with
        # 503+Retry-After, healthz reports draining so the router
        # stops routing here, and the engine thread lets active
        # decodes run until _drain_deadline.
        self._draining = threading.Event()
        self._drain_deadline: Optional[float] = None
        self._drain_reason = ""
        self._drain_engine_done = False
        self.drain_rejections = 0
        engine.on_token = self._on_token
        engine.on_admit = self._on_admit
        engine.on_shed = self._on_shed
        self._submit_q: "queue.Queue[_Pending]" = queue.Queue()
        self._inflight: dict[str, _Pending] = {}
        self._inflight_lock = threading.Lock()
        # Engine-side run ownership: request_id -> the _Pending whose
        # submission the engine is actually decoding. Written ONLY by
        # the engine thread; _engine_active mirrors its keys under
        # _inflight_lock so _make_pending can reject an id that is
        # still decoding (a client that timed out/disconnected and
        # retried must not receive the stale run's completion).
        self._active_runs: dict[str, _Pending] = {}
        self._engine_active: set[str] = set()
        # Cancellations cross onto the engine thread here (the engine
        # is single-threaded by design; cancel mutates slot state).
        self._cancel_q: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        # Live client sockets (handler setup/finish): kill() severs
        # them all to reproduce the SIGKILL failure shape — streams
        # end in a reset/bare EOF with no drain marker and no final
        # line, exactly what the router's recovery path must absorb.
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        # Recent-request detail only (bounded): totals and
        # percentiles come from the running counters + histograms
        # below, so a replica's memory/stats cost never grows with
        # lifetime traffic.
        import collections
        self._completed: "collections.deque" = collections.deque(
            maxlen=2048)
        # Finished-result replay cache (bounded), written atomically
        # with the _inflight pop under _inflight_lock: a resume that
        # races completion finds the cached result here instead of
        # being admitted as a fresh (duplicate) decode.
        self._recent_results: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._recent_results_cap = 2048
        self._total_completed = 0
        self._total_tokens = 0
        # Mergeable fixed-log-bucket latency histograms
        # (trace/histogram.py): the shape the router can aggregate
        # fleet-wide and Prometheus can histogram_quantile() over —
        # exact per-request lists stay only for this replica's own
        # recent detail.
        self._ttft_hist = LatencyHistogram()
        self._tpot_hist = LatencyHistogram()
        # Per-SLO-class attainment accounting (under _stats_lock):
        # class -> {requests, ttft_ok, tpot_ok, shed}.
        self._class_stats: dict[str, dict] = {}
        self._started_at = time.perf_counter()
        self._engine_thread = threading.Thread(
            target=self._engine_loop, name="serving-engine", daemon=True)
        front = self

        class Handler(JsonRequestHandler):
            def setup(self):
                super().setup()
                with front._conns_lock:
                    front._conns.add(self.connection)

            def finish(self):
                try:
                    super().finish()
                finally:
                    with front._conns_lock:
                        front._conns.discard(self.connection)

            def do_DELETE(self):  # noqa: N802
                request_id = self._delete_request_id()
                if request_id is None:
                    return
                # Unknown ids 404 so a fleet router's broadcast
                # cancel can keep probing replicas for the owner.
                if not front.knows(request_id):
                    self._reply(404, {"error": f"unknown request_id "
                                               f"{request_id}"})
                    return
                front.cancel(request_id)
                self._reply(202, {"request_id": request_id,
                                  "cancelling": True})

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    # Draining replicas answer 503 so the router's
                    # status==200 health check pulls them from
                    # rotation before the kill lands.
                    if front.draining:
                        self._reply(503, {"ok": False,
                                          "draining": True})
                    else:
                        self._reply(200, {"ok": True})
                elif self.path == "/metrics":
                    self._reply_metrics(front.prometheus_metrics())
                elif self.path == "/v1/stats":
                    self._reply(200, front.stats())
                elif self.path.startswith("/v1/requests/"):
                    # Liveness + progress of one request id (the
                    # fleet router's orphan reconciliation AND its
                    # mid-stream recovery probe this — one source of
                    # truth): 200 while the run is in flight here,
                    # 404 once finished or never seen.
                    request_id = self.path[len("/v1/requests/"):]
                    status = front.request_status(request_id)
                    if status is not None:
                        self._reply(200, status)
                    else:
                        self._reply(404, {"request_id": request_id,
                                          "in_flight": False})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if self.path != "/v1/generate":
                    self._reply(404, {"error": "not found"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    spec = json.loads(self.rfile.read(length))
                except (ValueError, OSError) as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                if not isinstance(spec, dict):
                    self._reply(400, {"error": "body must be a JSON "
                                               "object"})
                    return
                if spec.get("stream"):
                    # Owns its response lifecycle end-to-end; nothing
                    # here may write a second reply after its headers.
                    self._stream_generate(spec)
                    return
                try:
                    result = front.generate(spec)
                except CompletedReplay as exc:
                    # Resume of an already-finished run: exactly-once
                    # means replaying the cached result, not decoding
                    # a duplicate.
                    self._reply(200, dict(exc.result, cached=True))
                    return
                except RequestDraining as exc:
                    self._reply(503, {"error": str(exc),
                                      "draining": True},
                                headers={"Retry-After": "1"})
                    return
                except TooManyRequests as exc:
                    self._reply(429, {"error": str(exc),
                                      "backpressure": True},
                                headers={"Retry-After": "1"})
                    return
                except RequestCancelled as exc:
                    self._reply(409, {"error": str(exc)})
                    return
                except RequestShed as exc:
                    # Overload back-pressure, not failure: clients
                    # should retry elsewhere/later.
                    self._reply(503, {"error": str(exc),
                                      "shed": True})
                    return
                except ValueError as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                except Exception as exc:  # defensive: keep serving
                    logger.exception("generate failed")
                    self._reply(500, {"error": str(exc)})
                    return
                self._reply(200, result)

            def _stream_generate(self, spec: dict) -> None:
                """Newline-delimited JSON token stream over chunked
                transfer: the client sees each token the engine step
                that produced it, then the final result object.
                Validation errors before headers -> plain 400; errors
                AFTER the 200/chunked headers are emitted as a final
                {"error": ...} NDJSON line + clean terminating chunk
                (a second HTTP response inside the open stream would
                corrupt the framing)."""
                stream = None
                try:
                    request_id, stream = front.generate_stream(spec)
                except CompletedReplay as exc:
                    # Replay the cached run as a stream: the router's
                    # index dedupe drops what the client already saw.
                    result, request_id = exc.result, None
                except RequestDraining as exc:
                    self._reply(503, {"error": str(exc),
                                      "draining": True},
                                headers={"Retry-After": "1"})
                    return
                except TooManyRequests as exc:
                    self._reply(429, {"error": str(exc),
                                      "backpressure": True},
                                headers={"Retry-After": "1"})
                    return
                except ValueError as exc:
                    self._reply(400, {"error": str(exc)})
                    return
                except Exception as exc:  # defensive, like do_POST
                    logger.exception("stream setup failed")
                    self._reply(500, {"error": str(exc)})
                    return
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()
                except OSError:
                    # Client vanished before headers: the iterator
                    # never runs, so ITS cleanup never runs — drop
                    # the front-end registration explicitly (the
                    # engine-side guard still protects the id until
                    # decode completes).
                    if request_id is not None:
                        front.abandon(request_id)
                    return

                def _chunk(obj: dict) -> None:
                    line = json.dumps(obj).encode() + b"\n"
                    self.wfile.write(
                        f"{len(line):x}\r\n".encode() + line +
                        b"\r\n")
                    self.wfile.flush()

                if stream is None:
                    # CompletedReplay: token lines then the cached
                    # final result, same framing as a live stream.
                    try:
                        for i, token in enumerate(result["tokens"]):
                            _chunk({"token": token, "index": i})
                        _chunk(dict(result, cached=True))
                        self.wfile.write(b"0\r\n\r\n")
                    except (BrokenPipeError, ConnectionResetError):
                        pass
                    return
                try:
                    try:
                        for event in stream:
                            _chunk(event)
                    except (BrokenPipeError, ConnectionResetError):
                        # Client went away mid-relay: not a stream
                        # failure — the outer handler ignores it and
                        # the engine finishes the run on its own.
                        raise
                    except RequestDraining as exc:
                        # Mid-stream drain-abandon: the marker tells
                        # the router to resume on a sibling rather
                        # than surface a failure.
                        _chunk({"error": str(exc), "draining": True})
                    except RequestShed as exc:
                        _chunk({"error": str(exc), "shed": True})
                    except (ValueError, TimeoutError,
                            RequestCancelled) as exc:
                        _chunk({"error": str(exc)})
                    except Exception as exc:  # defensive
                        logger.exception("stream failed")
                        _chunk({"error": str(exc)})
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away; engine finishes anyway
                finally:
                    stream.close()  # run the iterator's cleanup NOW

        if io_timeout_s is not None:
            # socketserver applies Handler.timeout as the connection
            # socket timeout (settimeout) — per-request read/write
            # deadlines so a wedged client can't pin a thread.
            Handler.timeout = io_timeout_s
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)

    # ------------------------------ lifecycle --------------------------

    @property
    def address(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def start(self) -> "ServingFrontEnd":
        self._engine_thread.start()
        self._http_thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._engine_thread.join(timeout=10.0)

    def kill(self) -> None:
        """The SIGKILL failure shape (chaos drills): stop the engine,
        close the listening socket, AND sever every live client
        connection mid-write — no drain ladder, no draining markers,
        no final stream lines. Downstream (the fleet router) sees a
        reset or a bare EOF without a final line, which is exactly
        the signal its mid-stream recovery keys on."""
        self._stop.set()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._engine_thread.join(timeout=10.0)

    # ------------------------------ draining ---------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def drain(self, grace_s: Optional[float] = None,
              reason: str = "drain requested") -> None:
        """Flip this replica into the drain ladder: healthz turns
        503/draining (the router stops routing here), new admissions
        get 503+Retry-After, the engine stops seating queued work,
        and in-flight decodes get ``grace_s`` seconds to finish
        before they are abandoned with a draining marker (the router
        resumes them on a sibling). Idempotent."""
        if self._draining.is_set():
            return
        grace = self.drain_grace_s if grace_s is None else grace_s
        self._drain_deadline = time.perf_counter() + max(0.0, grace)
        self._drain_reason = reason
        self._draining.set()
        logger.info("serving front end draining (%s): grace %.1fs",
                    reason, grace)

    def arm_preempt_drain(self, path: Optional[str] = None,
                          grace_s: Optional[float] = None,
                          poll_interval: float = 0.2) -> bool:
        """Watch the node agent's preempt/evict notice file
        (agent/preemption.py: $SHIPYARD_PREEMPT_REQUEST_FILE) and
        drain when it lands — the serving analog of the training
        checkpoint-on-notice path. Returns False (unarmed) when no
        notice channel is configured."""
        from batch_shipyard_tpu.agent.preemption import PreemptWatcher
        watcher = PreemptWatcher(path)
        if not watcher.armed:
            return False

        def _watch() -> None:
            while not self._stop.is_set():
                notice = watcher.poll()
                if notice:
                    self.drain(
                        grace_s,
                        reason="preempt notice: "
                        f"{notice.get('reason') or 'unspecified'}")
                    return
                time.sleep(poll_interval)

        threading.Thread(target=_watch, name="serving-drain-watch",
                         daemon=True).start()
        return True

    # ------------------------------ serving ----------------------------

    def _make_pending(self, spec: dict,
                      stream: bool = False) -> _Pending:
        prompt = spec.get("prompt")
        if not isinstance(prompt, list) or not all(
                isinstance(t, int) for t in prompt):
            raise ValueError("prompt must be a list of token ids")
        resume = spec.get("resume_tokens")
        if resume is not None and (
                not isinstance(resume, list) or not all(
                    isinstance(t, int) for t in resume)):
            raise ValueError(
                "resume_tokens must be a list of token ids")
        request_id = str(spec.get("request_id") or uuid.uuid4().hex[:12])
        try:
            max_new_tokens = int(spec.get("max_new_tokens", 16))
            priority = int(spec.get("priority") or 0)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"max_new_tokens/priority must be integers: {exc}")
        slo_class = str(spec.get("slo_class") or "standard")
        if self.slo_classes and "slo_class" in spec and \
                slo_class not in self.slo_classes:
            raise ValueError(
                f"unknown slo_class {slo_class!r}; configured: "
                f"{sorted(self.slo_classes)}")
        targets = self.slo_classes.get(slo_class, {})

        def _target(key):
            value = spec.get(key, targets.get(
                key.replace("_target", "")))
            if value is None:
                return None
            try:
                return float(value)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"{key} must be a number: {exc}")

        request = Request(
            request_id=request_id, prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=spec.get("eos_id"),
            priority=priority,
            ttft_target_ms=_target("ttft_target_ms"),
            tpot_target_ms=_target("tpot_target_ms"),
            slo_class=slo_class)
        pending = _Pending(request, stream=stream, resumed=resume)
        with self._inflight_lock:
            if resume is not None and \
                    request_id in self._recent_results:
                # The prior replica's run actually finished here (the
                # failover raced completion): replay, don't re-decode.
                raise CompletedReplay(
                    self._recent_results[request_id])
            if (request_id in self._inflight or
                    request_id in self._engine_active):
                raise ValueError(f"request_id {request_id} in flight")
            if self._draining.is_set():
                self.drain_rejections += 1
                raise RequestDraining(
                    f"request {request_id} refused: replica draining"
                    f" ({self._drain_reason})")
            if (self.max_inflight is not None and resume is None and
                    len(self._inflight) >= self.max_inflight):
                raise TooManyRequests(
                    f"request {request_id} refused: "
                    f"{len(self._inflight)} in flight >= cap "
                    f"{self.max_inflight}")
            self._inflight[request_id] = pending
        if resume and (
                len(resume) >= request.max_new_tokens or
                (request.eos_id is not None and
                 resume[-1] == request.eos_id)):
            # The resumed progress already satisfies the request:
            # complete without touching the engine (callers skip
            # submission when the event is pre-set).
            pending.tokens = list(resume)
            pending.finished_at = time.perf_counter()
            pending.first_token_at = pending.finished_at
            if pending.token_queue is not None:
                pending.token_queue.put(None)
            pending.event.set()
        return pending

    def _result(self, pending: _Pending) -> dict:
        request_id = pending.request.request_id
        n = len(pending.tokens)
        ttft = (pending.first_token_at or pending.finished_at) - \
            pending.submitted_at
        decode = pending.finished_at - (pending.first_token_at or
                                        pending.submitted_at)
        tpot = decode / max(1, n - 1)
        result = {
            "request_id": request_id,
            "tokens": pending.tokens,
            "num_tokens": n,
            "ttft_ms": ttft * 1e3,
            "tpot_ms": tpot * 1e3,
            "latency_ms": (pending.finished_at -
                           pending.submitted_at) * 1e3,
            "slo_class": pending.request.slo_class,
        }
        req = pending.request
        with self._stats_lock:
            cls = self._class_stats.setdefault(
                req.slo_class,
                {"requests": 0, "ttft_ok": 0, "tpot_ok": 0,
                 "shed": 0})
            cls["requests"] += 1
            if req.ttft_target_ms is None or \
                    result["ttft_ms"] <= req.ttft_target_ms:
                cls["ttft_ok"] += 1
            if req.tpot_target_ms is None or \
                    result["tpot_ms"] <= req.tpot_target_ms:
                cls["tpot_ok"] += 1
            self._completed.append({
                "ttft_ms": result["ttft_ms"],
                "tpot_ms": result["tpot_ms"],
                "latency_ms": result["latency_ms"],
                "num_tokens": n,
            })
            self._total_completed += 1
            self._total_tokens += n
            self._ttft_hist.observe(result["ttft_ms"])
            self._tpot_hist.observe(result["tpot_ms"])
            seq = self._total_completed
        # Retire the registration and publish the replay-cache entry
        # under ONE lock hold: a resume landing between "popped from
        # _inflight" and "result visible" would otherwise be admitted
        # as a duplicate decode.
        with self._inflight_lock:
            self._recent_results[request_id] = result
            while len(self._recent_results) > self._recent_results_cap:
                self._recent_results.popitem(last=False)
            self._inflight.pop(request_id, None)
        self._record_request_spans(pending, result, seq)
        return result

    # Span head-sampling: the first _SPAN_HEAD requests record full
    # span chains, then 1-in-_SPAN_SAMPLE_EVERY. The HISTOGRAMS see
    # every request (percentiles are exact); only the per-request
    # span detail is sampled — a long-lived replica at high rate must
    # not grow its JSONL sink and TABLE_TRACE by 4 rows per request
    # forever (the goodput recorder this mirrors is low-rate by
    # nature; serving traffic is not).
    _SPAN_HEAD = 512
    _SPAN_SAMPLE_EVERY = 16

    def _record_request_spans(self, pending: _Pending,
                              result: dict, seq: int) -> None:
        """Per-request trace spans (admit -> queued -> prefill ->
        decode), recorded through the process-local JSONL recorder —
        a no-op outside pool tasks (no $SHIPYARD_TRACE_* context), so
        standalone servers pay nothing."""
        if trace_spans.local_spans_path() is None:
            return
        if seq > self._SPAN_HEAD and seq % self._SPAN_SAMPLE_EVERY:
            return
        request_id = pending.request.request_id
        t0 = pending.submitted_wall

        def wall(perf: Optional[float]) -> float:
            return (t0 if perf is None
                    else t0 + perf - pending.submitted_at)

        parent = trace_spans.record(
            trace_spans.SPAN_SERVE_REQUEST, t0,
            wall(pending.finished_at), request_id=request_id,
            num_tokens=result["num_tokens"],
            ttft_ms=result["ttft_ms"], tpot_ms=result["tpot_ms"])
        if parent is None:
            return
        admitted = wall(pending.admitted_at)
        trace_spans.record(
            trace_spans.SPAN_SERVE_QUEUED, t0, admitted,
            parent_span_id=parent, request_id=request_id)
        first = wall(pending.first_token_at)
        trace_spans.record(
            trace_spans.SPAN_SERVE_PREFILL, admitted, first,
            parent_span_id=parent, request_id=request_id,
            prompt_len=len(pending.request.prompt))
        decode_attrs = {"request_id": request_id,
                        "num_tokens": result["num_tokens"],
                        "tpot_ms": result["tpot_ms"]}
        # Speculative accept/rewind detail rides the decode span
        # (engine-level counters: acceptance is not tracked per
        # request, so this is the engine's running view at
        # completion).
        spec = self.engine.spec_stats()
        if spec is not None:
            decode_attrs["spec_gamma"] = spec["gamma"]
            decode_attrs["spec_acceptance_rate"] = \
                spec["acceptance_rate"]
            decode_attrs["spec_rewinds"] = (
                spec["proposed"] - spec["accepted"])
        trace_spans.record(
            trace_spans.SPAN_SERVE_DECODE, first,
            wall(pending.finished_at), parent_span_id=parent,
            **decode_attrs)

    def generate_stream(self, spec: dict, timeout: float = 300.0):
        """Streaming generate: yields {"token", "index"} per decoded
        token, then the final result object (generate()'s payload).
        Validation happens HERE (before any bytes hit the wire) — the
        returned iterator only pulls tokens."""
        pending = self._make_pending(spec, stream=True)
        if not pending.event.is_set():  # pre-satisfied resumes skip
            self._submit_q.put(pending)
        return (pending.request.request_id,
                self._stream_tokens(pending, timeout))

    def abandon(self, request_id: str) -> None:
        """Drop the front-end registration of a request whose client
        went away before its stream ever started (the engine keeps
        decoding; _engine_active still blocks id reuse meanwhile)."""
        with self._inflight_lock:
            self._inflight.pop(request_id, None)

    def _stream_tokens(self, pending: _Pending, timeout: float):
        request_id = pending.request.request_id
        try:
            while True:
                try:
                    item = pending.token_queue.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError(
                        f"request {request_id} timed out after "
                        f"{timeout}s")
                if item is None:
                    break
                index, token = item
                yield {"token": token, "index": index}
            self._wait_complete(pending, timeout)
        except BaseException:
            # Error/cancel/close path retires the registration here;
            # the success path retires it inside _result, atomically
            # with the replay-cache publish (racing-resume guard).
            with self._inflight_lock:
                self._inflight.pop(request_id, None)
            raise
        yield self._result(pending)

    def _wait_complete(self, pending: _Pending,
                       timeout: float) -> None:
        """Shared completion protocol: wait for the engine to finish
        the run, surface engine-side errors."""
        if not pending.event.wait(timeout):
            raise TimeoutError(
                f"request {pending.request.request_id} timed out "
                f"after {timeout}s")
        if pending.draining:
            raise RequestDraining(pending.error)
        if pending.cancelled:
            raise RequestCancelled(pending.error)
        if pending.shed:
            raise RequestShed(pending.error)
        if pending.error is not None:
            raise ValueError(pending.error)

    def prometheus_metrics(self) -> list[str]:
        """Serving metrics in Prometheus exposition format — add this
        front end (or the fleet router) as a scrape target of the
        monitoring stack (docs/09-monitoring.md) to chart TTFT/TPOT
        next to the node-exporter panels."""
        stats = self.stats()
        lines = prometheus_lines("shipyard_serving", {
            "completed_requests_total": stats["completed_requests"],
            "generated_tokens_total": stats["generated_tokens"],
            "tokens_per_second": stats["tokens_per_second"],
            "uptime_seconds": stats["uptime_seconds"],
            "inflight": stats["inflight"],
            "engine_backlog": stats["engine_backlog"],
            "draining": 1.0 if stats["draining"] else 0.0,
            "drain_rejections_total": stats["drain_rejections"],
        })
        for metric in ("ttft_ms", "tpot_ms"):
            for pct, value in stats[metric].items():
                lines.extend(prometheus_lines(
                    "shipyard_serving", {metric: value},
                    labels={"quantile": f"0.{pct}"}))
        # Native histogram exposition (cumulative _bucket/_sum/_count)
        # so histogram_quantile() works on the scrape and fleet-level
        # aggregation is sound.
        with self._stats_lock:
            for metric, hist in (("ttft_ms", self._ttft_hist),
                                 ("tpot_ms", self._tpot_hist)):
                lines.extend(hist.prometheus_bucket_lines(
                    f"shipyard_serving_{metric}"))
        spec = stats.get("speculative")
        if spec:
            lines.extend(prometheus_lines("shipyard_serving", {
                "spec_rounds_total": spec["rounds"],
                "spec_proposed_tokens_total": spec["proposed"],
                "spec_accepted_tokens_total": spec["accepted"],
                "spec_acceptance_rate": spec["acceptance_rate"],
            }))
        prefix = stats.get("prefix_cache")
        if prefix:
            lines.extend(prometheus_lines("shipyard_serving", {
                "prefix_hit_rate": prefix["hit_rate"],
                "prefix_hit_tokens_total": prefix["hit_tokens"],
                "prefix_prompt_tokens_total":
                    prefix["total_prompt_tokens"],
                "prefix_indexed_pages": prefix["indexed_pages"],
                "prefix_published_pages_total":
                    prefix["published_pages"],
                "prefix_evictions_total": prefix["evictions"],
            }))
        slo = stats.get("slo") or {}
        lines.extend(prometheus_lines("shipyard_serving", {
            "slo_sheds_total": slo.get("sheds"),
            "slo_deferrals_total": slo.get("deferrals"),
        }))
        for name, counters in (slo.get("classes") or {}).items():
            lines.extend(prometheus_lines(
                "shipyard_serving", {
                    "slo_class_requests_total": counters["requests"],
                    "slo_class_ttft_ok_total": counters["ttft_ok"],
                    "slo_class_tpot_ok_total": counters["tpot_ok"],
                    "slo_class_shed_total": counters["shed"],
                }, labels={"slo_class": name}))
        return lines

    def knows(self, request_id: str) -> bool:
        """Whether this front end currently owns the request (in
        flight or actively decoding)."""
        with self._inflight_lock:
            return (request_id in self._inflight or
                    request_id in self._engine_active)

    def request_status(self, request_id: str) -> Optional[dict]:
        """Progress of one in-flight request — the shared source of
        truth for the router's resubmit probe and its mid-stream
        recovery: phase (queued/prefill/decode/draining) and the
        emitted-token count. None once finished or never seen (the
        404 the router's orphan reconciliation keys on)."""
        with self._inflight_lock:
            pending = self._inflight.get(request_id)
            if pending is None and request_id in self._engine_active:
                # Abandoned stream still decoding: the engine-side
                # run holds the progress.
                pending = self._active_runs.get(request_id)
        if pending is None:
            return None
        if self._draining.is_set():
            phase = "draining"
        elif pending.admitted_at is None:
            phase = "queued"
        elif pending.emitted <= len(pending.resumed or []):
            phase = "prefill"
        else:
            phase = "decode"
        return {"request_id": request_id, "in_flight": True,
                "phase": phase,
                "emitted_tokens": int(pending.emitted)}

    def cancel(self, request_id: str) -> None:
        """Request an abort; the engine thread performs it and the
        waiting client completes with a 'cancelled' error."""
        self._cancel_q.put(request_id)

    def generate(self, spec: dict, timeout: float = 300.0) -> dict:
        """Blocking generate: enqueue to the engine thread, wait for
        completion, return tokens + latency breakdown."""
        pending = self._make_pending(spec)
        if not pending.event.is_set():  # pre-satisfied resumes skip
            self._submit_q.put(pending)
        try:
            self._wait_complete(pending, timeout)
        except BaseException:
            with self._inflight_lock:
                self._inflight.pop(pending.request.request_id, None)
            raise
        return self._result(pending)

    def stats(self) -> dict:
        with self._stats_lock:
            completed = self._total_completed
            tokens = self._total_tokens
            ttft_hist = self._ttft_hist.to_dict()
            tpot_hist = self._tpot_hist.to_dict()
            ttft_pcts = self._ttft_hist.percentiles((50, 90, 99))
            tpot_pcts = self._tpot_hist.percentiles((50, 90, 99))
            class_stats = {name: dict(counters) for name, counters
                           in self._class_stats.items()}
        elapsed = time.perf_counter() - self._started_at
        with self._inflight_lock:
            inflight = len(self._inflight)
        out = {
            "completed_requests": completed,
            "generated_tokens": tokens,
            "uptime_seconds": elapsed,
            "tokens_per_second": tokens / elapsed if elapsed else 0.0,
            # Percentiles come from the fixed-bucket histograms (the
            # same numbers any fleet-level merge reproduces), keyed
            # p50/p90/p99; the raw bucket counts ride along so the
            # router can merge replicas losslessly.
            "ttft_ms": {p: ttft_pcts[f"p{p}"] for p in (50, 90, 99)},
            "tpot_ms": {p: tpot_pcts[f"p{p}"] for p in (50, 90, 99)},
            "ttft_hist": ttft_hist,
            "tpot_hist": tpot_hist,
            # Router observability (models/router.py polls these):
            # requests this front end has accepted but not completed,
            # and the engine's queued+active total.
            "inflight": inflight,
            "engine_backlog": self.engine.pending(),
            # Drain ladder visibility: the router's probe reads
            # "draining" to distinguish cooperative shutdown from
            # failure.
            "draining": self._draining.is_set(),
            "drain_rejections": self.drain_rejections,
        }
        # Speculative-decode counters when the engine runs a draft
        # model (the measured acceptance rate is the tuning signal
        # for gamma and draft sizing; the router aggregates these
        # fleet-wide).
        spec = self.engine.spec_stats()
        if spec is not None:
            out["speculative"] = spec
        # Request-level SLO scheduling: per-class attainment plus the
        # engine's shed/deferral counters and live cost estimates.
        engine_slo = self.engine.slo_stats()
        out["slo"] = {
            "classes": {
                name: dict(
                    counters,
                    targets=self.slo_classes.get(name),
                    ttft_attainment=(
                        counters["ttft_ok"] / counters["requests"]
                        if counters["requests"] else None),
                    tpot_attainment=(
                        counters["tpot_ok"] / counters["requests"]
                        if counters["requests"] else None))
                for name, counters in class_stats.items()},
            **engine_slo,
        }
        # Prefix-cache effectiveness (None when the engine runs
        # dense or with the cache disabled); the router aggregates
        # hit_tokens/total_prompt_tokens fleet-wide.
        prefix = self.engine.prefix_stats()
        if prefix is not None:
            out["prefix_cache"] = prefix
        return out

    # --------------------------- engine thread -------------------------

    def _on_admit(self, request_id: str) -> None:
        # Engine-thread hook (inside engine.step's _admit): stamps
        # the queued -> prefill boundary of the request's span chain.
        pending = self._active_runs.get(request_id)
        if pending is not None and pending.admitted_at is None:
            pending.admitted_at = time.perf_counter()

    def _on_shed(self, request_id: str, reason: str) -> None:
        # Engine-thread hook (inside engine.step's _shed_expired):
        # the engine dropped a queued request under overload —
        # complete its waiter as shed (503) and count it against its
        # class's attainment.
        pending = self._active_runs.pop(request_id, None)
        with self._inflight_lock:
            self._engine_active.discard(request_id)
        if pending is None:
            return
        with self._stats_lock:
            cls = self._class_stats.setdefault(
                pending.request.slo_class,
                {"requests": 0, "ttft_ok": 0, "tpot_ok": 0,
                 "shed": 0})
            cls["shed"] += 1
        pending.error = f"request {request_id} shed: {reason}"
        pending.shed = True
        pending.finished_at = time.perf_counter()
        if pending.token_queue is not None:
            pending.token_queue.put(None)
        pending.event.set()

    def _on_token(self, request_id: str, token: int, index: int) -> None:
        # _active_runs is engine-thread-owned and this hook runs on
        # the engine thread (inside engine.step) — no lock needed,
        # and completions can never be attributed to a retried
        # request's NEW pending while the old run still decodes.
        pending = self._active_runs.get(request_id)
        if pending is None:
            return
        if pending.first_token_at is None:
            # First token THIS replica produced — for a resumed run
            # that is the re-prefill completion (index > 0), still
            # the TTFT that matters here.
            pending.first_token_at = time.perf_counter()
        pending.emitted = max(pending.emitted, index + 1)
        if pending.token_queue is not None:
            pending.token_queue.put((index, token))

    def _engine_loop(self) -> None:
        while not self._stop.is_set():
            # Park only when fully idle; with active slots the loop
            # must spin at full decode rate — a blocking get here
            # would throttle every active request's TPOT.
            if not self.engine.pending():
                try:
                    self._submit(self._submit_q.get(timeout=0.2))
                except queue.Empty:
                    pass
            while True:
                try:
                    self._submit(self._submit_q.get_nowait())
                except queue.Empty:
                    break
            while True:
                try:
                    self._cancel(self._cancel_q.get_nowait())
                except queue.Empty:
                    break
            if self._draining.is_set():
                self._drain_tick()
            if not self.engine.pending():
                continue
            try:
                finished = self.engine.step()
            except Exception:
                logger.exception("engine step failed")
                continue
            now = time.perf_counter()
            for request_id, tokens in finished:
                pending = self._active_runs.pop(request_id, None)
                with self._inflight_lock:
                    self._engine_active.discard(request_id)
                if pending is None:
                    continue
                pending.tokens = tokens
                pending.finished_at = now
                if pending.token_queue is not None:
                    pending.token_queue.put(None)  # end of stream
                pending.event.set()

    def _drain_tick(self) -> None:
        # Engine-thread side of the drain ladder: evict the queue
        # once (those waiters fail over immediately — they hold no
        # pages and no progress), then let active decodes run until
        # the grace deadline, after which they are abandoned with a
        # draining marker the router resumes from.
        if not self._drain_engine_done:
            for request_id in self.engine.drain():
                self._complete_draining(
                    request_id, "queued work evicted at drain")
            self._drain_engine_done = True
            return
        if self._drain_deadline is not None and \
                time.perf_counter() >= self._drain_deadline:
            for request_id in self.engine.active_request_ids():
                self._cancel(request_id, draining=True)

    def _complete_draining(self, request_id: str, why: str) -> None:
        pending = self._active_runs.pop(request_id, None)
        with self._inflight_lock:
            self._engine_active.discard(request_id)
        if pending is None:
            return
        pending.error = f"request {request_id} draining: {why}"
        pending.draining = True
        pending.finished_at = time.perf_counter()
        if pending.token_queue is not None:
            pending.token_queue.put(None)
        pending.event.set()

    def _cancel(self, request_id: str,
                draining: bool = False) -> None:
        if not self.engine.cancel(request_id):
            return  # unknown/already finished
        pending = self._active_runs.pop(request_id, None)
        with self._inflight_lock:
            self._engine_active.discard(request_id)
        if pending is None:
            return
        if draining:
            pending.error = (f"request {request_id} draining: grace "
                             f"deadline, decode abandoned")
            pending.draining = True
        else:
            pending.error = f"request {request_id} cancelled"
            pending.cancelled = True
        pending.finished_at = time.perf_counter()
        if pending.token_queue is not None:
            pending.token_queue.put(None)
        pending.event.set()

    def _submit(self, pending: _Pending) -> None:
        if self._draining.is_set() or self.engine.draining:
            # Drain ladder: requests already queued toward the engine
            # when the notice landed must not be admitted — complete
            # their waiters as draining so the router fails over.
            request_id = pending.request.request_id
            pending.error = (f"request {request_id} draining: not "
                             f"admitted, replica shutting down")
            pending.draining = True
            pending.finished_at = time.perf_counter()
            if pending.token_queue is not None:
                pending.token_queue.put(None)
            pending.event.set()
            return
        try:
            self.engine.submit(pending.request,
                               resumed=pending.resumed)
        except ValueError as exc:
            pending.error = str(exc)
            pending.finished_at = time.perf_counter()
            if pending.token_queue is not None:
                pending.token_queue.put(None)
            pending.event.set()
            return
        request_id = pending.request.request_id
        self._active_runs[request_id] = pending
        with self._inflight_lock:
            self._engine_active.add(request_id)

"""Shared scheduling policies (goodput-as-controller).

`sched.policy` holds the PURE decision functions — claim scoring,
victim selection, autoscale targets — imported by BOTH the live
agent/autoscale paths and the discrete-event fleet simulator
(`batch_shipyard_tpu/sim/`), so the simulator exercises production
decision code rather than a fork of it.
"""

"""Local-filesystem state store: cross-process, single-host.

Used for the localhost substrate (real task execution on this machine,
e.g. the bench path that drives the one attached TPU chip) and for
multi-process integration tests. Correctness across processes comes
from an exclusive ``fcntl.flock`` around each mutation of the JSON
metadata databases, with object payloads stored as content files and
atomic ``os.replace`` writes.

This mirrors the role the reference gives Azure Storage (all shared
state; convoy/storage.py) at laptop scale — the GCS store (gcs.py) is
the cloud-scale implementation with identical semantics.
"""

from __future__ import annotations

import contextlib
import fcntl
import hashlib
import json
import os
import time
import uuid
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state import base
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, LeaseHandle, LeaseLostError,
    NotFoundError, ObjectMeta, PreconditionFailedError, QueueMessage)
from batch_shipyard_tpu.utils.util import atomic_write as _atomic_write


class LocalFSStateStore(base.StateStore):
    def __init__(self, root: str) -> None:
        self._root = os.path.abspath(root)
        os.makedirs(os.path.join(self._root, "objects"), exist_ok=True)
        self._lockfile = os.path.join(self._root, ".lock")
        # Touch the lock file once.
        with open(self._lockfile, "a", encoding="utf-8"):
            pass

    # ------------------------- locking + dbs ---------------------------

    @contextlib.contextmanager
    def _locked(self):
        with open(self._lockfile, "r+", encoding="utf-8") as fh:
            fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(fh.fileno(), fcntl.LOCK_UN)

    def _db_path(self, name: str) -> str:
        return os.path.join(self._root, f"{name}.json")

    def _load_db(self, name: str) -> dict:
        path = self._db_path(name)
        if not os.path.exists(path):
            return {}
        with open(path, "r", encoding="utf-8") as fh:
            content = fh.read()
        if not content.strip():
            return {}
        return json.loads(content)

    def _save_db(self, name: str, db: dict) -> None:
        _atomic_write(self._db_path(name),
                      json.dumps(db).encode("utf-8"))

    def _object_path(self, key: str) -> str:
        digest = hashlib.sha256(key.encode("utf-8")).hexdigest()
        return os.path.join(self._root, "objects", digest)

    # ------------------------------ objects ----------------------------

    def put_object(self, key: str, data: bytes,
                   if_generation_match: Optional[int] = None) -> int:
        with self._locked():
            db = self._load_db("objects")
            meta = db.get(key)
            if if_generation_match is not None:
                cur_gen = meta["generation"] if meta else 0
                if cur_gen != if_generation_match:
                    raise PreconditionFailedError(
                        f"{key}: generation {cur_gen} != "
                        f"{if_generation_match}")
            counter = db.get("\x00counter", 0) + 1
            db["\x00counter"] = counter
            _atomic_write(self._object_path(key), data)
            db[key] = {"generation": counter, "size": len(data),
                       "updated": time.time()}
            self._save_db("objects", db)
            return counter

    def put_object_stream(self, key, chunks,
                          if_generation_match=None) -> int:
        """True streaming: chunks are written incrementally to a temp
        file next to the target, then renamed under the lock — the
        whole object never sits in memory."""
        path = self._object_path(key)
        tmp = f"{path}.stream.{os.getpid()}"
        os.makedirs(os.path.dirname(path), exist_ok=True)
        size = 0
        try:
            with open(tmp, "wb") as fh:
                for chunk in chunks:
                    fh.write(chunk)
                    size += len(chunk)
                # Mirror _atomic_write: flush+fsync BEFORE the locked
                # os.replace, so a crash between the rename and the
                # page cache landing can never surface a torn object
                # under a committed metadata row.
                fh.flush()
                os.fsync(fh.fileno())
            with self._locked():
                db = self._load_db("objects")
                meta = db.get(key)
                if if_generation_match is not None:
                    cur_gen = meta["generation"] if meta else 0
                    if cur_gen != if_generation_match:
                        raise PreconditionFailedError(
                            f"{key}: generation {cur_gen} != "
                            f"{if_generation_match}")
                counter = db.get("\x00counter", 0) + 1
                db["\x00counter"] = counter
                os.replace(tmp, path)
                db[key] = {"generation": counter, "size": size,
                           "updated": time.time()}
                self._save_db("objects", db)
                return counter
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def get_object_stream(self, key, chunk_size=None):
        chunk_size = chunk_size or self.STREAM_CHUNK_BYTES
        with self._locked():
            db = self._load_db("objects")
            if key not in db or key == "\x00counter":
                raise NotFoundError(key)
            path = self._object_path(key)
        try:
            with open(path, "rb") as fh:
                while True:
                    chunk = fh.read(chunk_size)
                    if not chunk:
                        return
                    yield chunk
        except FileNotFoundError:
            raise NotFoundError(key)

    def get_object(self, key: str) -> bytes:
        with self._locked():
            db = self._load_db("objects")
            if key not in db or key == "\x00counter":
                raise NotFoundError(key)
            try:
                with open(self._object_path(key), "rb") as fh:
                    return fh.read()
            except FileNotFoundError:
                raise NotFoundError(key)

    def get_object_meta(self, key: str) -> ObjectMeta:
        with self._locked():
            db = self._load_db("objects")
            if key not in db or key == "\x00counter":
                raise NotFoundError(key)
            meta = db[key]
        import datetime
        return ObjectMeta(
            key=key, size=meta["size"], generation=meta["generation"],
            updated=datetime.datetime.fromtimestamp(
                meta["updated"], datetime.timezone.utc))

    def delete_object(self, key: str,
                      if_generation_match: Optional[int] = None) -> None:
        with self._locked():
            db = self._load_db("objects")
            if key not in db or key == "\x00counter":
                raise NotFoundError(key)
            if if_generation_match is not None and (
                    db[key]["generation"] != if_generation_match):
                raise PreconditionFailedError(key)
            del db[key]
            with contextlib.suppress(FileNotFoundError):
                os.remove(self._object_path(key))
            self._save_db("objects", db)

    def list_objects(self, prefix: str = "") -> list[str]:
        with self._locked():
            db = self._load_db("objects")
        return sorted(k for k in db
                      if k != "\x00counter" and k.startswith(prefix))

    # ------------------------------ leases -----------------------------

    def acquire_lease(self, key: str, duration_seconds: float,
                      owner: str) -> Optional[LeaseHandle]:
        now = time.time()
        with self._locked():
            db = self._load_db("leases")
            held = db.get(key)
            if held is not None and held["expires_at"] > now:
                return None
            token = uuid.uuid4().hex
            expires = now + duration_seconds
            db[key] = {"owner": owner, "token": token, "expires_at": expires}
            self._save_db("leases", db)
            return LeaseHandle(key=key, owner=owner, token=token,
                               expires_at=expires)

    def renew_lease(self, handle: LeaseHandle,
                    duration_seconds: float) -> LeaseHandle:
        now = time.time()
        with self._locked():
            db = self._load_db("leases")
            held = db.get(handle.key)
            if held is None or held["token"] != handle.token or (
                    held["expires_at"] <= now):
                raise LeaseLostError(handle.key)
            expires = now + duration_seconds
            db[handle.key]["expires_at"] = expires
            self._save_db("leases", db)
            return LeaseHandle(key=handle.key, owner=handle.owner,
                               token=handle.token, expires_at=expires)

    def release_lease(self, handle: LeaseHandle) -> None:
        with self._locked():
            db = self._load_db("leases")
            held = db.get(handle.key)
            if held is None or held["token"] != handle.token:
                raise LeaseLostError(handle.key)
            del db[handle.key]
            self._save_db("leases", db)

    # ------------------------------ tables -----------------------------

    @staticmethod
    def _ekey(pk: str, rk: str) -> str:
        return f"{pk}\x01{rk}"

    def insert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        with self._locked():
            db = self._load_db(f"table_{table}")
            key = self._ekey(partition_key, row_key)
            if key in db:
                raise EntityExistsError(f"{table}:{partition_key}:{row_key}")
            etag = uuid.uuid4().hex
            db[key] = {"entity": entity, "etag": etag}
            self._save_db(f"table_{table}", db)
            return etag

    def upsert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        with self._locked():
            db = self._load_db(f"table_{table}")
            etag = uuid.uuid4().hex
            db[self._ekey(partition_key, row_key)] = {
                "entity": entity, "etag": etag}
            self._save_db(f"table_{table}", db)
            return etag

    def merge_entity(self, table: str, partition_key: str, row_key: str,
                     entity: dict[str, Any],
                     if_match: Optional[str] = None) -> str:
        with self._locked():
            db = self._load_db(f"table_{table}")
            key = self._ekey(partition_key, row_key)
            if key not in db:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            if if_match is not None and db[key]["etag"] != if_match:
                raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
            merged = dict(db[key]["entity"])
            merged.update(entity)
            etag = uuid.uuid4().hex
            db[key] = {"entity": merged, "etag": etag}
            self._save_db(f"table_{table}", db)
            return etag

    def get_entity(self, table: str, partition_key: str,
                   row_key: str) -> dict[str, Any]:
        with self._locked():
            db = self._load_db(f"table_{table}")
            key = self._ekey(partition_key, row_key)
            if key not in db:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            out = dict(db[key]["entity"])
            out["_etag"] = db[key]["etag"]
            out["_pk"] = partition_key
            out["_rk"] = row_key
            return out

    def query_entities(self, table: str,
                       partition_key: Optional[str] = None,
                       row_key_prefix: str = "",
                       ) -> Iterator[dict[str, Any]]:
        with self._locked():
            db = self._load_db(f"table_{table}")
        for key in sorted(db):
            pk, _, rk = key.partition("\x01")
            if partition_key is not None and pk != partition_key:
                continue
            if row_key_prefix and not rk.startswith(row_key_prefix):
                continue
            out = dict(db[key]["entity"])
            out["_etag"] = db[key]["etag"]
            out["_pk"] = pk
            out["_rk"] = rk
            yield out

    def delete_entity(self, table: str, partition_key: str, row_key: str,
                      if_match: Optional[str] = None) -> None:
        with self._locked():
            db = self._load_db(f"table_{table}")
            key = self._ekey(partition_key, row_key)
            if key not in db:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            if if_match is not None and db[key]["etag"] != if_match:
                raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
            del db[key]
            self._save_db(f"table_{table}", db)

    # ------------------------------ queues -----------------------------

    def put_message(self, queue: str, payload: bytes,
                    delay_seconds: float = 0.0) -> str:
        with self._locked():
            db = self._load_db(f"queue_{queue}")
            message_id = uuid.uuid4().hex
            msgs = db.setdefault("messages", [])
            msgs.append({
                "id": message_id,
                "payload": payload.hex(),
                "visible_at": time.time() + delay_seconds,
                "dequeue_count": 0,
                "receipt": None,
            })
            self._save_db(f"queue_{queue}", db)
            return message_id

    def put_messages(self, queue: str, payloads: list[bytes],
                     delay_seconds: float = 0.0) -> list[str]:
        """Single lock/load/save for the whole batch (one fsync
        instead of N — the dominant cost of per-message puts)."""
        with self._locked():
            db = self._load_db(f"queue_{queue}")
            msgs = db.setdefault("messages", [])
            ids = []
            visible = time.time() + delay_seconds
            for payload in payloads:
                message_id = uuid.uuid4().hex
                msgs.append({
                    "id": message_id, "payload": payload.hex(),
                    "visible_at": visible, "dequeue_count": 0,
                    "receipt": None})
                ids.append(message_id)
            self._save_db(f"queue_{queue}", db)
            return ids

    def insert_entities(self, table: str,
                        rows: list[tuple[str, str, dict]]) -> list[str]:
        with self._locked():
            db = self._load_db(f"table_{table}")
            etags = []
            for pk, rk, entity in rows:
                key = self._ekey(pk, rk)
                if key in db:
                    raise EntityExistsError(f"{table}:{pk}:{rk}")
                etag = uuid.uuid4().hex
                db[key] = {"entity": entity, "etag": etag}
                etags.append(etag)
            self._save_db(f"table_{table}", db)
            return etags

    def count_entities_by(self, table: str, partition_key: str,
                          column: str = "state") -> dict[str, int]:
        """One db load, no per-row result dicts (the summary-poll
        fast path; see base.count_entities_by)."""
        with self._locked():
            db = self._load_db(f"table_{table}")
        counts: dict[str, int] = {}
        prefix = f"{partition_key}\x01"
        for key, record in db.items():
            if not key.startswith(prefix):
                continue
            value = str(record["entity"].get(column) or "")
            counts[value] = counts.get(value, 0) + 1
        return counts

    def get_messages(self, queue: str, max_messages: int = 1,
                     visibility_timeout: float = 30.0,
                     ) -> list[QueueMessage]:
        now = time.time()
        out: list[QueueMessage] = []
        with self._locked():
            db = self._load_db(f"queue_{queue}")
            for msg in db.get("messages", []):
                if len(out) >= max_messages:
                    break
                if msg["visible_at"] > now:
                    continue
                msg["visible_at"] = now + visibility_timeout
                msg["dequeue_count"] += 1
                msg["receipt"] = uuid.uuid4().hex
                out.append(QueueMessage(
                    queue=queue, message_id=msg["id"],
                    pop_receipt=msg["receipt"],
                    payload=bytes.fromhex(msg["payload"]),
                    dequeue_count=msg["dequeue_count"]))
            if out:
                self._save_db(f"queue_{queue}", db)
        return out

    def delete_message(self, message: QueueMessage) -> None:
        with self._locked():
            db = self._load_db(f"queue_{message.queue}")
            msgs = db.get("messages", [])
            for msg in msgs:
                if msg["id"] == message.message_id:
                    if msg["receipt"] != message.pop_receipt:
                        raise NotFoundError(message.message_id)
                    msgs.remove(msg)
                    self._save_db(f"queue_{message.queue}", db)
                    return
            raise NotFoundError(message.message_id)

    def update_message(self, message: QueueMessage,
                       visibility_timeout: float) -> QueueMessage:
        with self._locked():
            db = self._load_db(f"queue_{message.queue}")
            for msg in db.get("messages", []):
                if msg["id"] == message.message_id:
                    if msg["receipt"] != message.pop_receipt:
                        raise NotFoundError(message.message_id)
                    msg["visible_at"] = time.time() + visibility_timeout
                    self._save_db(f"queue_{message.queue}", db)
                    return message
            raise NotFoundError(message.message_id)

    def queue_length(self, queue: str) -> int:
        with self._locked():
            db = self._load_db(f"queue_{queue}")
            return len(db.get("messages", []))

    def clear(self) -> None:
        import shutil
        with self._locked():
            for name in os.listdir(self._root):
                if name == ".lock":
                    continue
                path = os.path.join(self._root, name)
                if os.path.isdir(path):
                    shutil.rmtree(path)
                else:
                    os.remove(path)
            os.makedirs(os.path.join(self._root, "objects"), exist_ok=True)

"""Tests for the strict schema engine and the shipped config schemas."""

import pytest

from batch_shipyard_tpu.config import validator
from batch_shipyard_tpu.config.validator import (
    ConfigType, ValidationError, validate, validate_config)


def test_scalar_types():
    schema = {"type": "map", "mapping": {
        "a": {"type": "str"}, "b": {"type": "int"}, "c": {"type": "bool"},
        "d": {"type": "number"}}}
    assert validate({"a": "x", "b": 1, "c": True, "d": 2.5}, schema) == []
    errs = validate({"a": 1, "b": "x", "c": 2, "d": "y"}, schema)
    assert len(errs) == 4


def test_bool_is_not_int():
    schema = {"type": "map", "mapping": {"n": {"type": "int"}}}
    assert validate({"n": True}, schema)


def test_unknown_key_rejected_strict():
    schema = {"type": "map", "mapping": {"a": {"type": "str"}}}
    errs = validate({"a": "x", "zz": 1}, schema)
    assert any("unknown key" in e for e in errs)


def test_allow_unknown():
    schema = {"type": "map", "allow_unknown": True, "mapping": {}}
    assert validate({"anything": 1}, schema) == []


def test_required_key():
    schema = {"type": "map", "mapping": {
        "a": {"type": "str", "required": True}}}
    errs = validate({}, schema)
    assert any("required" in e for e in errs)


def test_enum_pattern_range():
    schema = {"type": "map", "mapping": {
        "e": {"type": "str", "enum": ["x", "y"]},
        "p": {"type": "str", "pattern": "[a-z]+"},
        "r": {"type": "int", "range": {"min": 1, "max": 5}}}}
    assert validate({"e": "x", "p": "abc", "r": 3}, schema) == []
    errs = validate({"e": "z", "p": "ABC", "r": 9}, schema)
    assert len(errs) == 3


def test_seq_and_nullable():
    schema = {"type": "map", "mapping": {
        "s": {"type": "seq", "sequence": {"type": "int"}},
        "n": {"type": "str", "nullable": True}}}
    assert validate({"s": [1, 2], "n": None}, schema) == []
    assert validate({"s": [1, "x"]}, schema)


def test_pool_schema_good():
    config = {"pool_specification": {
        "id": "mypool",
        "tpu": {"accelerator_type": "v5litepod-16"},
    }}
    assert validate_config(ConfigType.POOL, config) == []


def test_pool_schema_bad_key():
    config = {"pool_specification": {
        "id": "mypool", "not_a_real_key": 1}}
    with pytest.raises(ValidationError) as exc:
        validate_config(ConfigType.POOL, config)
    assert "not_a_real_key" in str(exc.value)


def test_jobs_schema_good():
    config = {"job_specifications": [{
        "id": "job1",
        "tasks": [{
            "docker_image": "busybox",
            "command": "echo hi",
            "multi_instance": {
                "num_instances": 4,
                "jax_distributed": {"enabled": True, "transport": "ici"},
            },
        }],
    }]}
    assert validate_config(ConfigType.JOBS, config) == []


def test_credentials_schema():
    config = {"credentials": {
        "gcp": {"project": "my-proj"},
        "storage": {"backend": "localfs", "root": "/tmp/x"},
    }}
    assert validate_config(ConfigType.CREDENTIALS, config) == []
    bad = {"credentials": {"storage": {"backend": "s3"}}}
    with pytest.raises(ValidationError):
        validate_config(ConfigType.CREDENTIALS, bad)


def test_all_schemas_parse():
    for ct in ConfigType:
        assert validator._load_schema(ct.value) is not None


def test_federation_logging_block_placement():
    """proxy_options.logging {level, persistence} validates; the old
    misplaced polling_interval.level is rejected (strict unknown-key
    rule) — the schema bug the round-5 docs sync uncovered."""
    good = {"federation": {"proxy_options": {
        "polling_interval": {"federations": 5, "actions": 1},
        "logging": {"level": "debug", "persistence": True}}}}
    validate_config(ConfigType.FEDERATION, good)
    bad = {"federation": {"proxy_options": {
        "polling_interval": {"actions": 1, "level": "debug"}}}}
    with pytest.raises(ValidationError):
        validate_config(ConfigType.FEDERATION, bad)

"""Fused RMSNorm+matmul kernel tests (interpret mode): forward vs the
unfused XLA composition, custom_vjp gradients vs autodiff of the
reference, and end-to-end model-loss equivalence of the fused_norm
transformer path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.ops import fused_norm as fn


def _ref_compose(x, scale, w, eps=1e-6):
    return jnp.dot(fn.rmsnorm_ref(x, scale, eps), w,
                   preferred_element_type=jnp.float32).astype(x.dtype)


@pytest.mark.parametrize("m,k,n", [(64, 256, 384), (40, 128, 128),
                                   (256, 512, 1152)])
def test_forward_matches_reference(m, k, n):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * rng.randn(k), jnp.float32)
    w = jnp.asarray(rng.randn(k, n) / np.sqrt(k), jnp.float32)
    got = fn.rmsnorm_matmul(x, scale, w, impl="interpret")
    want = _ref_compose(x, scale, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_bf16():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(64, 256), jnp.bfloat16)
    scale = jnp.asarray(1.0 + 0.1 * rng.randn(256), jnp.float32)
    w = jnp.asarray(rng.randn(256, 128) / 16, jnp.bfloat16)
    got = fn.rmsnorm_matmul(x, scale, w, impl="interpret")
    want = _ref_compose(x, scale, w)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=0.05, atol=0.05)


def test_gradients_match_autodiff():
    """custom_vjp backward (hand-derived RMSNorm chain rule) vs plain
    autodiff through the unfused composition."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(48, 128), jnp.float32)
    scale = jnp.asarray(1.0 + 0.1 * rng.randn(128), jnp.float32)
    w = jnp.asarray(rng.randn(128, 256) / 12, jnp.float32)
    tgt = jnp.asarray(rng.randn(48, 256), jnp.float32)

    def loss_fused(x_, s_, w_):
        y = fn.rmsnorm_matmul(x_, s_, w_, 1e-6, 256, 512, "xla")
        return jnp.sum((y - tgt) ** 2)

    def loss_ref(x_, s_, w_):
        return jnp.sum((_ref_compose(x_, s_, w_) - tgt) ** 2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, w)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, w)
    for got, want, name in zip(gf, gr, ("dx", "dscale", "dw")):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4,
            err_msg=name)


def test_fused_norm_model_matches_unfused():
    """A fused_norm TransformerLM with weights transplanted from the
    unfused model produces the same loss and comparable grads."""
    from batch_shipyard_tpu.models import transformer as tfm

    cfg_kw = dict(vocab_size=128, d_model=128, n_layers=2, n_heads=4,
                  d_head=32, d_ff=256, max_seq_len=64,
                  dtype=jnp.float32)
    base = tfm.TransformerConfig(**cfg_kw)
    fused = tfm.TransformerConfig(fused_norm=True, **cfg_kw)
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, 128, (2, 64)), jnp.int32)
    targets = jnp.asarray(
        np.random.RandomState(4).randint(0, 128, (2, 64)), jnp.int32)
    params = tfm.TransformerLM(base).init(
        jax.random.PRNGKey(0), tokens)["params"]

    # Transplant: per-projection Dense kernels -> merged fused params.
    fused_params = {}
    for name, sub in params.items():
        if not name.startswith("layer_"):
            fused_params[name] = sub
            continue
        attn = sub["attn"]
        layer = {
            "attn": {
                "norm_scale": sub["attn_norm"]["scale"],
                "qkv_kernel": jnp.concatenate(
                    [attn["q_proj"]["kernel"], attn["k_proj"]["kernel"],
                     attn["v_proj"]["kernel"]], axis=1),
                "o_proj": attn["o_proj"],
            },
            "mlp": {
                "norm_scale": sub["mlp_norm"]["scale"],
                "gate_up_kernel": jnp.concatenate(
                    [sub["mlp"]["gate_proj"]["kernel"],
                     sub["mlp"]["up_proj"]["kernel"]], axis=1),
                "down_proj": sub["mlp"]["down_proj"],
            },
        }
        fused_params[name] = layer

    def loss_fn(model_cfg, p):
        logits = tfm.TransformerLM(model_cfg).apply(
            {"params": p}, tokens)
        return tfm.lm_loss(logits, targets)

    l_base, g_base = jax.value_and_grad(
        lambda p: loss_fn(base, p))(params)
    l_fused, g_fused = jax.value_and_grad(
        lambda p: loss_fn(fused, p))(fused_params)
    np.testing.assert_allclose(float(l_base), float(l_fused),
                               rtol=1e-5)
    # Spot-check one merged gradient against the unfused pieces.
    gq = g_base["layer_0"]["attn"]["q_proj"]["kernel"]
    gqkv = g_fused["layer_0"]["attn"]["qkv_kernel"]
    np.testing.assert_allclose(
        np.asarray(gqkv[:, : gq.shape[1]]), np.asarray(gq),
        rtol=1e-4, atol=1e-5)
    gscale_base = g_base["layer_0"]["attn_norm"]["scale"]
    gscale_fused = g_fused["layer_0"]["attn"]["norm_scale"]
    np.testing.assert_allclose(
        np.asarray(gscale_fused), np.asarray(gscale_base),
        rtol=1e-4, atol=1e-5)


def test_fused_norm_rejects_bad_compositions():
    from batch_shipyard_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, d_model=64, n_layers=1, n_heads=2, d_head=32,
        d_ff=128, fused_norm=True, quantize_matmuls=True,
        dtype=jnp.float32)
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(NotImplementedError):
        tfm.TransformerLM(cfg).init(jax.random.PRNGKey(0), tokens)

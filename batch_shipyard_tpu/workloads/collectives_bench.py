"""Collective microbenchmark payload: the mpiBench/OSU recipe analog.

Times psum/all_gather/ppermute/reduce_scatter over the device mesh and
prints per-size bus bandwidth. Over a pod slice this measures the ICI
fabric the way mpiBench measured Infiniband.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.collectives_bench \
        --sizes 65536,1048576,16777216
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

from batch_shipyard_tpu.ops import collectives
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", default="65536,1048576,16777216",
                        help="comma-separated message sizes in bytes")
    parser.add_argument("--ops",
                        default="psum,all_gather,ppermute,"
                                "reduce_scatter")
    parser.add_argument("--dtype", default="bfloat16")
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    if n_dev < 2:
        distributed.log(ctx, "single device: collective bench is a "
                             "no-op loopback")
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(n_dev))
    rows = collectives.run_collective_bench(
        mesh, axis="dp",
        ops=tuple(args.ops.split(",")),
        sizes_bytes=tuple(int(s) for s in args.sizes.split(",")),
        dtype=getattr(jnp, args.dtype))
    if jax.process_index() == 0:
        for row in rows:
            print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""shipyard-tpu CLI: the click command tree.

Reference analog: shipyard.py (3136 LoC click tree: pool/jobs/data/
storage/diag/monitor/fed/slurm groups, shipyard.py:1001-3136). Groups
mirror the reference so a Batch Shipyard user finds the same verbs:

  shipyard-tpu pool   add | list | del | resize | nodes | stats | ssh |
                      images update | autoscale ...
  shipyard-tpu jobs   add | list | term | del | stats | wait |
                      tasks list
  shipyard-tpu goodput job | pool | fleet
  shipyard-tpu trace  show | export | prune
  shipyard-tpu chaos  plan | drill
  shipyard-tpu data   stream | ingress
  shipyard-tpu diag   perf
  shipyard-tpu storage clear
  shipyard-tpu monitor / fed / slurm (aux clusters)
"""

from __future__ import annotations

import os
import sys

import click

from batch_shipyard_tpu import fleet
from batch_shipyard_tpu.chaos import plan as chaos_plan_mod
from batch_shipyard_tpu.version import __version__


@click.group(context_settings={"help_option_names": ["-h", "--help"]})
@click.version_option(version=__version__)
@click.option("--configdir", envvar="SHIPYARD_CONFIGDIR", default=None,
              help="Directory holding credentials/config/pool/jobs yaml")
@click.option("--credentials", "credentials_path", default=None,
              help="Path to credentials yaml")
@click.option("--config", "config_path", default=None,
              help="Path to global config yaml")
@click.option("--pool", "pool_path", default=None,
              help="Path to pool yaml")
@click.option("--jobs", "jobs_path", default=None,
              help="Path to jobs yaml")
@click.option("--raw", is_flag=True, default=False,
              help="JSON output for scripting")
@click.pass_context
def cli(click_ctx, configdir, credentials_path, config_path, pool_path,
        jobs_path, raw):
    files = {}
    if credentials_path:
        files["credentials"] = credentials_path
    if config_path:
        files["config"] = config_path
    if pool_path:
        files["pool"] = pool_path
    if jobs_path:
        files["jobs"] = jobs_path
    click_ctx.obj = {
        "configdir": configdir, "files": files, "raw": raw, "ctx": None}


def _ctx(click_ctx) -> fleet.Context:
    if click_ctx.obj["ctx"] is None:
        click_ctx.obj["ctx"] = fleet.load_context(
            click_ctx.obj["configdir"], click_ctx.obj["files"])
    return click_ctx.obj["ctx"]


# ------------------------------- pool ----------------------------------

@cli.group()
def pool():
    """Pool lifecycle (TPU pod slices / VM groups)."""


@pool.command("add")
@click.option("--no-wait", is_flag=True, default=False)
@click.pass_context
def pool_add(click_ctx, no_wait):
    """Provision the pool from pool.yaml."""
    fleet.action_pool_add(_ctx(click_ctx), wait=not no_wait)


@pool.command("list")
@click.pass_context
def pool_list(click_ctx):
    fleet.action_pool_list(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@pool.command("del")
@click.option("--pool-id", default=None)
@click.option("-y", "--yes", is_flag=True, default=False)
@click.pass_context
def pool_del(click_ctx, pool_id, yes):
    ctx = _ctx(click_ctx)
    target = pool_id or ctx.pool.id
    if not yes and not click.confirm(
            f"Delete pool {target} and all its jobs/tasks?"):
        raise click.Abort()
    fleet.action_pool_del(ctx, pool_id)


@pool.command("resize")
@click.argument("num_slices", type=int)
@click.pass_context
def pool_resize(click_ctx, num_slices):
    fleet.action_pool_resize(_ctx(click_ctx), num_slices)


@pool.command("exists")
@click.option("--pool-id", default=None)
@click.pass_context
def pool_exists(click_ctx, pool_id):
    """Exit 0 if the pool exists, 1 otherwise (reference
    `pool exists`)."""
    from batch_shipyard_tpu.pool import manager as pool_mgr
    ctx = _ctx(click_ctx)
    target = pool_id or ctx.pool.id
    if pool_mgr.pool_exists(ctx.store, target):
        click.echo(f"pool {target} exists")
    else:
        click.echo(f"pool {target} does not exist")
        raise SystemExit(1)


@pool.command("stats")
@click.pass_context
def pool_stats(click_ctx):
    fleet.action_pool_stats(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@pool.group()
def nodes():
    """Node operations."""


@nodes.command("list")
@click.pass_context
def pool_nodes_list(click_ctx):
    fleet.action_pool_nodes_list(_ctx(click_ctx),
                                 raw=click_ctx.obj["raw"])


@nodes.command("count")
@click.pass_context
def pool_nodes_count(click_ctx):
    """Node-state histogram (reference `pool nodes count`)."""
    fleet.action_pool_nodes_count(_ctx(click_ctx),
                                  raw=click_ctx.obj["raw"])


@nodes.command("grls")
@click.option("--node-id", default=None)
@click.pass_context
def pool_nodes_grls(click_ctx, node_id):
    """Remote-login settings (ip/port) for nodes (reference
    `pool nodes grls`)."""
    fleet.action_pool_nodes_grls(_ctx(click_ctx), node_id,
                                 raw=click_ctx.obj["raw"])


@nodes.command("ps")
@click.option("--node-id", default=None)
@click.pass_context
def pool_nodes_ps(click_ctx, node_id):
    """List running tasks/containers on nodes (reference
    `pool nodes ps`)."""
    fleet.action_pool_nodes_ps(_ctx(click_ctx), node_id,
                               raw=click_ctx.obj["raw"])


@nodes.command("zap")
@click.option("--node-id", default=None)
@click.option("-y", "--yes", is_flag=True)
@click.pass_context
def pool_nodes_zap(click_ctx, node_id, yes):
    """Kill all live task processes/containers on nodes (reference
    `pool nodes zap`)."""
    if not yes:
        click.confirm(
            f"zap all running work on "
            f"{node_id or 'ALL nodes'}?", abort=True)
    fleet.action_pool_nodes_zap(_ctx(click_ctx), node_id,
                                raw=click_ctx.obj["raw"])


@nodes.command("prune")
@click.option("--node-id", default=None)
@click.pass_context
def pool_nodes_prune(click_ctx, node_id):
    """Prune unreferenced image-cache entries on nodes (reference
    `pool nodes prune`)."""
    fleet.action_pool_nodes_prune(_ctx(click_ctx), node_id,
                                  raw=click_ctx.obj["raw"])


@nodes.command("reboot")
@click.argument("node_id")
@click.option("-y", "--yes", is_flag=True)
@click.pass_context
def pool_nodes_reboot(click_ctx, node_id, yes):
    """Reboot a node (recreates its whole TPU slice; reference
    `pool nodes reboot`)."""
    if not yes:
        click.confirm(f"reboot {node_id}'s slice?", abort=True)
    fleet.action_pool_nodes_reboot(_ctx(click_ctx), node_id)


@nodes.command("del")
@click.argument("node_id")
@click.option("-y", "--yes", is_flag=True)
@click.pass_context
def pool_nodes_del(click_ctx, node_id, yes):
    """Delete a node (deallocates its whole TPU slice without
    replacement; reference `pool nodes del`)."""
    if not yes:
        click.confirm(f"deallocate {node_id}'s slice?", abort=True)
    fleet.action_pool_nodes_del(_ctx(click_ctx), node_id)


@pool.command("ssh")
@click.argument("node_id")
@click.pass_context
def pool_ssh(click_ctx, node_id):
    fleet.action_pool_ssh(_ctx(click_ctx), node_id)


@pool.command("suspend")
@click.pass_context
def pool_suspend(click_ctx):
    """Stop the pool's machines without deleting the pool."""
    fleet.action_pool_suspend(_ctx(click_ctx))


@pool.command("start")
@click.pass_context
def pool_start(click_ctx):
    """Restart a suspended pool."""
    fleet.action_pool_start(_ctx(click_ctx))


@pool.group("cache")
def pool_cache():
    """Warm-start compile-cache seeding (docs/29-compile-cache.md)."""


@pool_cache.command("stats")
@click.pass_context
def pool_cache_stats(click_ctx):
    """Seed-artifact state: identity, entries, bytes, uploader."""
    fleet.action_pool_cache_stats(_ctx(click_ctx),
                                  raw=click_ctx.obj["raw"])


@pool_cache.command("seed")
@click.option("--cache-dir",
              default=os.environ.get("SHIPYARD_COMPILE_CACHE_DIR")
              or "./compilecache", show_default=True,
              help="local cache dir to seed from the pool artifact")
@click.pass_context
def pool_cache_seed(click_ctx, cache_dir):
    """Seed a local cache dir from the pool's artifact (refuses a
    mismatched cache identity)."""
    fleet.action_pool_cache_seed(_ctx(click_ctx), cache_dir,
                                 raw=click_ctx.obj["raw"])


@pool_cache.command("prune")
@click.option("-y", "--yes", is_flag=True)
@click.pass_context
def pool_cache_prune(click_ctx, yes):
    """Delete the pool's compile-cache artifacts (stale-cache escape
    hatch after jax/model upgrades)."""
    if not yes:
        click.confirm("prune the pool's compile-cache artifacts?",
                      abort=True)
    fleet.action_pool_cache_prune(_ctx(click_ctx),
                                  raw=click_ctx.obj["raw"])


@pool.group()
def user():
    """SSH user management on pool nodes."""


@user.command("add")
@click.option("--username", default="shipyard")
@click.option("--output-dir", default=".")
@click.pass_context
def pool_user_add(click_ctx, username, output_dir):
    private_path, _public = fleet.action_pool_user_add(
        _ctx(click_ctx), username, output_dir)
    click.echo(f"private key: {private_path}")


@user.command("del")
@click.option("--username", default="shipyard")
@click.pass_context
def pool_user_del(click_ctx, username):
    fleet.action_pool_user_del(_ctx(click_ctx), username)


@pool.group()
def images():
    """Container image management on pool nodes."""


@images.command("list")
@click.pass_context
def pool_images_list(click_ctx):
    """List the pool's replicated image manifest."""
    ctx = _ctx(click_ctx)
    from batch_shipyard_tpu.state import names as names_mod
    images = []
    registries = []
    for r in ctx.store.query_entities(names_mod.TABLE_IMAGES,
                                      partition_key=ctx.pool.id):
        if r.get("kind") == "registry":
            # Credential rows ride the same manifest; list them as a
            # separate section (never their secret material).
            registries.append({"server": r.get("server")})
        else:
            images.append({"kind": r.get("kind"),
                           "image": r.get("image")})
    fleet._emit({"images": images, "registries": registries},
                click_ctx.obj["raw"])


@images.command("update")
@click.argument("image")
@click.option("--kind", default="docker",
              type=click.Choice(["docker", "singularity"]))
@click.pass_context
def pool_images_update(click_ctx, image, kind):
    fleet.action_pool_images_update(_ctx(click_ctx), image, kind)


@pool.group()
def autoscale():
    """Pool autoscale management."""


@autoscale.command("enable")
@click.pass_context
def pool_autoscale_enable(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    as_mod.enable_autoscale(_ctx(click_ctx).store, _ctx(click_ctx).pool)


@autoscale.command("disable")
@click.pass_context
def pool_autoscale_disable(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    as_mod.disable_autoscale(_ctx(click_ctx).store, _ctx(click_ctx).pool)


@autoscale.command("evaluate")
@click.pass_context
def pool_autoscale_evaluate(click_ctx):
    from batch_shipyard_tpu.pool import autoscale as as_mod
    ctx = _ctx(click_ctx)
    decision = as_mod.evaluate(ctx.store, ctx.pool)
    fleet._emit(decision, click_ctx.obj["raw"])


@autoscale.command("tick")
@click.option("--daemon", is_flag=True, default=False,
              help="Loop at autoscale.evaluation_interval_seconds")
@click.option("--interval", type=float, default=None,
              help="Override evaluation interval seconds")
@click.pass_context
def pool_autoscale_tick(click_ctx, daemon, interval):
    """Evaluate AND apply the autoscale decision (the hosted
    evaluator's job in the reference)."""
    from batch_shipyard_tpu.pool import autoscale as as_mod
    ctx = _ctx(click_ctx)
    if daemon:
        as_mod.run_daemon(ctx.store, ctx.substrate(), ctx.pool,
                          interval=interval)
    else:
        decision = as_mod.autoscale_tick(ctx.store, ctx.substrate(),
                                         ctx.pool)
        fleet._emit(decision, click_ctx.obj["raw"])


# ------------------------------- jobs ----------------------------------

@cli.group()
def jobs():
    """Job and task submission."""


@jobs.command("add")
@click.option("--tail", default=None,
              help="Stream this file of the last task after submit")
@click.pass_context
def jobs_add(click_ctx, tail):
    fleet.action_jobs_add(_ctx(click_ctx), tail=tail)


@jobs.command("autopool-reap")
@click.pass_context
def jobs_autopool_reap(click_ctx):
    """Delete auto pools whose job has completed."""
    reaped = fleet.action_autopool_reap(_ctx(click_ctx))
    click.echo(f"reaped: {reaped}")


@jobs.command("list")
@click.pass_context
def jobs_list(click_ctx):
    fleet.action_jobs_list(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@jobs.command("term")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_term(click_ctx, job_id):
    fleet.action_jobs_term(_ctx(click_ctx), job_id)


@jobs.command("del")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_del(click_ctx, job_id):
    fleet.action_jobs_del(_ctx(click_ctx), job_id)


@jobs.command("stats")
@click.option("--job-id", default=None)
@click.pass_context
def jobs_stats(click_ctx, job_id):
    fleet.action_jobs_stats(_ctx(click_ctx), job_id,
                            raw=click_ctx.obj["raw"])


@jobs.command("wait")
@click.option("--job-id", required=True)
@click.option("--timeout", type=float, default=600.0)
@click.option("--goodput-report", is_flag=True, default=False,
              help="Print the job's goodput decomposition once all "
                   "tasks are terminal")
@click.pass_context
def jobs_wait(click_ctx, job_id, timeout, goodput_report):
    """Block until every task of a job is terminal."""
    try:
        fleet.action_jobs_wait(_ctx(click_ctx), job_id,
                               timeout=timeout,
                               goodput_report=goodput_report,
                               raw=click_ctx.obj["raw"])
    except TimeoutError as exc:
        raise click.ClickException(str(exc))


@jobs.command("disable")
@click.option("--job-id", required=True)
@click.pass_context
def jobs_disable(click_ctx, job_id):
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    jobs_mgr.disable_job(ctx.store, ctx.pool.id, job_id)


@jobs.command("enable")
@click.option("--job-id", required=True)
@click.pass_context
def jobs_enable(click_ctx, job_id):
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    jobs_mgr.enable_job(ctx.store, ctx.pool.id, job_id)


@jobs.command("migrate")
@click.option("--job-id", required=True)
@click.option("--dst-pool-id", required=True)
@click.pass_context
def jobs_migrate(click_ctx, job_id, dst_pool_id):
    """Move a job's pending tasks to another pool."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    moved = jobs_mgr.migrate_job(ctx.store, ctx.pool.id, job_id,
                                 dst_pool_id)
    click.echo(f"migrated {moved} tasks of {job_id} to {dst_pool_id}")


@jobs.command("cmi")
@click.pass_context
def jobs_cmi(click_ctx):
    """Clean up orphaned multi-instance containers on all nodes."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    count = jobs_mgr.cleanup_mi_containers(ctx.store, ctx.pool.id)
    click.echo(f"cleanup fanned out to {count} nodes")


@jobs.command("profile")
@click.argument("job_id")
@click.option("--steps", type=int, default=10,
              help="Number of train steps to capture with "
                   "jax.profiler")
@click.pass_context
def jobs_profile(click_ctx, job_id, steps):
    """Request an on-demand profile of a job's tasks: the next N
    steps run under jax.profiler.trace and the artifact uploads next
    to the task's diagnostics (see `jobs tasks list`)."""
    fleet.action_jobs_profile(_ctx(click_ctx), job_id, steps=steps)


@jobs.command("preempt")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--reason", default="",
              help="Recorded on the preempt notice (diagnostics)")
@click.pass_context
def jobs_preempt(click_ctx, job_id, task_id, reason):
    """Cooperatively preempt a running task: it drains to its next
    step boundary, forces a COMMITTED checkpoint, and exits with the
    distinct preempted status — requeued at FULL retry budget with
    node health untouched (the preempt sweep's manual override)."""
    fleet.action_jobs_preempt(_ctx(click_ctx), job_id, task_id,
                              reason=reason)


@jobs.command("schedule")
@click.option("--once", is_flag=True, default=False,
              help="Evaluate due schedules once and exit")
@click.option("--poll-interval", type=float, default=5.0)
@click.pass_context
def jobs_schedule(click_ctx, once, poll_interval):
    """Run the recurrence scheduler for jobs with a recurrence block."""
    from batch_shipyard_tpu.jobs import schedules
    ctx = _ctx(click_ctx)
    if once:
        launched = schedules.run_due_schedules(ctx.store, ctx.pool,
                                               ctx.jobs)
        click.echo(f"launched: {launched}")
    else:
        schedules.run_schedule_daemon(ctx.store, ctx.pool, ctx.jobs,
                                      poll_interval=poll_interval)


@jobs.group()
def tasks():
    """Task operations."""


@tasks.command("list")
@click.argument("job_id")
@click.pass_context
def jobs_tasks_list(click_ctx, job_id):
    fleet.action_jobs_tasks_list(_ctx(click_ctx), job_id,
                                 raw=click_ctx.obj["raw"])


@tasks.command("del")
@click.argument("job_id")
@click.argument("task_id")
@click.pass_context
def jobs_tasks_del(click_ctx, job_id, task_id):
    """Delete a terminal task's entity and uploaded files."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    try:
        jobs_mgr.delete_task(ctx.store, ctx.pool.id, job_id, task_id)
    except (jobs_mgr.JobNotFoundError, ValueError) as exc:
        raise click.ClickException(str(exc))


@tasks.command("count")
@click.argument("job_id")
@click.pass_context
def tasks_count(click_ctx, job_id):
    """Task counts by state for a job (reference `jobs tasks
    count`)."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    try:
        stats = jobs_mgr.job_stats(ctx.store, ctx.pool.id, job_id)
    except jobs_mgr.JobNotFoundError:
        raise click.ClickException(f"job {job_id} does not exist")
    fleet._emit({"job_id": job_id, "total": stats["tasks"],
                 "by_state": stats["by_state"]},
                click_ctx.obj["raw"])


@tasks.command("term")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--wait", is_flag=True, default=False)
@click.pass_context
def jobs_tasks_term(click_ctx, job_id, task_id, wait):
    """Terminate a single task (kills its process on the node)."""
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    jobs_mgr.terminate_task(ctx.store, ctx.pool.id, job_id, task_id,
                            wait=wait)


# ------------------------------ goodput --------------------------------

@cli.group()
def goodput():
    """ML productivity goodput accounting (arxiv 2502.06982): badput
    waterfall + availability x resource x program decomposition over
    the fleet-wide event log."""


@goodput.command("job")
@click.argument("job_id")
@click.option("--trace", "trace_id", default=None,
              help="Scope the waterfall to one submission's trace id "
                   "(see `jobs tasks list` / `trace show`)")
@click.pass_context
def goodput_job(click_ctx, job_id, trace_id):
    """One job's decomposition (queue/image-pull/compile/checkpoint/
    rework badput vs productive step time)."""
    fleet.action_goodput(_ctx(click_ctx), "job", job_id=job_id,
                         raw=click_ctx.obj["raw"],
                         trace_id=trace_id)


@goodput.command("pool")
@click.pass_context
def goodput_pool(click_ctx):
    """Pool rollup (node lifecycle included) + per-job subreports."""
    fleet.action_goodput(_ctx(click_ctx), "pool",
                         raw=click_ctx.obj["raw"])


@goodput.command("fleet")
@click.pass_context
def goodput_fleet(click_ctx):
    """Fleet rollup over every registered pool."""
    fleet.action_goodput(_ctx(click_ctx), "fleet",
                         raw=click_ctx.obj["raw"])


@goodput.command("prune")
@click.option("--older-than-hours", type=float, default=7 * 24.0,
              help="Delete events that ended more than this many "
                   "hours ago (default: one week)")
@click.pass_context
def goodput_prune(click_ctx, older_than_hours):
    """Retention sweep over the pool's event log (the log is
    append-only; accounting scans grow with fleet age)."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    ctx = _ctx(click_ctx)
    removed = goodput_events.prune(ctx.store, ctx.pool.id,
                                   older_than_hours * 3600.0)
    click.echo(f"pruned {removed} events from pool {ctx.pool.id}")


# ------------------------------- trace ---------------------------------

@cli.group()
def trace():
    """End-to-end distributed tracing (trace/): the causal chain of
    one `jobs add` submission — queue wait, claim, backoff,
    rendezvous, program phases, serving requests — assembled from
    TABLE_TRACE spans + trace-tagged goodput intervals."""


@trace.command("show")
@click.argument("trace_id")
@click.pass_context
def trace_show(click_ctx, trace_id):
    """Terminal span waterfall for one trace id."""
    fleet.action_trace_show(_ctx(click_ctx), trace_id,
                            raw=click_ctx.obj["raw"])


@trace.command("export")
@click.argument("trace_id")
@click.option("--output", "-o", default=None,
              help="Write the Chrome trace JSON here (default: "
                   "stdout); load it in chrome://tracing or "
                   "ui.perfetto.dev")
@click.pass_context
def trace_export(click_ctx, trace_id, output):
    """Export one trace as Perfetto-loadable Chrome trace-event
    JSON."""
    fleet.action_trace_export(_ctx(click_ctx), trace_id,
                              output=output)


@trace.command("prune")
@click.option("--older-than-hours", type=float, default=7 * 24.0,
              help="Delete spans that ended more than this many "
                   "hours ago (default: one week)")
@click.pass_context
def trace_prune(click_ctx, older_than_hours):
    """Retention sweep over the pool's span log (same rule as
    `goodput prune`)."""
    from batch_shipyard_tpu.trace import spans as trace_spans_mod
    ctx = _ctx(click_ctx)
    removed = trace_spans_mod.prune(ctx.store, ctx.pool.id,
                                    older_than_hours * 3600.0)
    click.echo(f"pruned {removed} spans from pool {ctx.pool.id}")


# -------------------------------- lint ---------------------------------

@cli.command("lint")
@click.option("--baseline-update", is_flag=True, default=False,
              help="Rewrite .shipyard-lint-baseline.json from the "
                   "current findings (sorted, path-relative, "
                   "deterministic)")
@click.option("--rules", default="",
              help="Comma-separated rule ids to run (default all)")
@click.option("--list-rules", is_flag=True, default=False,
              help="Print the rule inventory with bug provenance")
@click.pass_context
def lint(click_ctx, baseline_update, rules, list_rules):
    """Run the distributed-invariant static analyzer (docs/34):
    store-race, hot-loop, env-contract, goodput/trace-registry, JAX,
    wiring, and shell rules over this source tree. Exits 1 on any
    finding not in the checked-in baseline; suppress intentional
    sites inline with `# shipyard-lint: disable=<rule-id>`."""
    rule_ids = tuple(r.strip() for r in rules.split(",")
                     if r.strip()) or None
    if baseline_update and rule_ids:
        raise click.UsageError(
            "--baseline-update rewrites the WHOLE baseline and "
            "cannot be combined with --rules")
    if rule_ids:
        # A flag typo must read as a usage error, not as findings.
        from batch_shipyard_tpu import analysis
        unknown = [r for r in rule_ids if r not in analysis.RULES]
        if unknown:
            raise click.UsageError(
                f"unknown rule(s) {', '.join(unknown)}; see "
                f"`shipyard-tpu lint --list-rules`")
    report = fleet.action_lint(
        None, baseline_update=baseline_update, rules=rule_ids,
        list_rules=list_rules, raw=click_ctx.obj["raw"])
    if not baseline_update and not list_rules and \
            not report.get("clean", True):
        raise SystemExit(1)


# ------------------------------- chaos ---------------------------------

@cli.group()
def chaos():
    """Deterministic chaos engineering (chaos/): seeded fault
    schedules replayed against a self-contained fakepod pool, with
    the self-healing invariants asserted (every task completes
    exactly once, no orphaned coordination state, goodput partition
    exact)."""


def _parse_kinds(kinds: str):
    return tuple(k.strip() for k in kinds.split(",") if k.strip()) \
        or None


@chaos.command("plan")
@click.option("--seed", type=int, default=0,
              help="Schedule seed (same seed, same injections)")
@click.option("--duration", type=float, default=4.0,
              help="Drill window in seconds (must match the drill's "
                   "for fingerprint parity)")
@click.option("--num-nodes", type=int, default=4,
              help="Logical node count targets are drawn from")
@click.option("--kinds", default="",
              help="Comma-separated injection kinds, default all: "
                   + ",".join(chaos_plan_mod.INJECTION_KINDS))
@click.option("--injections-per-kind", type=int, default=1)
@click.pass_context
def chaos_plan(click_ctx, seed, duration, num_nodes, kinds,
               injections_per_kind):
    """Render the deterministic fault schedule for a seed (no pool,
    no execution — review what a drill would inject)."""
    fleet.action_chaos_plan(
        None, seed, duration=duration, num_nodes=num_nodes,
        kinds=_parse_kinds(kinds),
        injections_per_kind=injections_per_kind,
        raw=click_ctx.obj["raw"])


@chaos.command("drill")
@click.option("--seed", type=int, default=0,
              help="Schedule seed (same seed, same injections)")
@click.option("--tasks", type=int, default=16,
              help="Tasks submitted to the drill pool")
@click.option("--duration", type=float, default=4.0,
              help="Injection window in seconds")
@click.option("--kinds", default="",
              help="Comma-separated injection kinds, default all: "
                   + ",".join(chaos_plan_mod.INJECTION_KINDS))
@click.option("--injections-per-kind", type=int, default=1)
@click.option("--preempt", is_flag=True, default=False,
              help="Run the preemption drill instead: a seeded "
                   "node_preempt_notice schedule against a running "
                   "4-node gang — cooperative drain, forced "
                   "COMMITTED checkpoint, zero lost steps, retry "
                   "budget and node health untouched")
@click.option("--victim", is_flag=True, default=False,
              help="Run the victim-selection drill: two eligible "
                   "victims (warm never-committer vs per-step "
                   "committer) under a higher-priority starver — "
                   "the sweep's goodput-cost ordering must elect "
                   "the CHEAP victim against the id tie-break")
@click.option("--evict", is_flag=True, default=False,
              help="Run the forcible-eviction drill: a seeded "
                   "victim_ignore_notice schedule against an "
                   "--ignore-notice probe — hard kill after the "
                   "grace window, exit classified evicted (full "
                   "budget, neutral health), resume from the "
                   "pre-notice COMMITTED barrier, eviction leg "
                   "priced")
@click.option("--resize", is_flag=True, default=False,
              help="Run the multi-host resize drill: a seeded "
                   "host_loss_resize schedule permanently crashes "
                   "one host of a 2-host sharded gang — elastic "
                   "re-form at 1 host, per-host reshard-on-restore "
                   "plan followed exactly, bit-exact state, "
                   "loss-trajectory oracle")
@click.option("--migrate", is_flag=True, default=False,
              help="Run the cross-pool migration drill: a seeded "
                   "pool_capacity_loss schedule crashes every node "
                   "under a federated gang — the elastic evaluator "
                   "re-targets it onto the sibling pool, one trace "
                   "spans the migration, migration leg priced")
@click.option("--outage", is_flag=True, default=False,
              help="Run the store-outage drill: a seeded "
                   "store_outage schedule takes the state store "
                   "DOWN for a sustained window — resilient-store "
                   "agents ride it out with zero retries, zero "
                   "lost advisory events (WAL replay), drained "
                   "journals, and the store_outage leg priced with "
                   "the exact window")
@click.option("--partition", is_flag=True, default=False,
              help="Run the leader-partition drill: a seeded "
                   "leader_partition schedule stalls the preempt-"
                   "sweep leader's heartbeats/lease renewals while "
                   "its sweep keeps running — exactly one "
                   "preemption stamp fires, carrying the successor "
                   "term's fencing epoch, with exactly one live "
                   "lease epoch at the end")
@click.option("--restart", is_flag=True, default=False,
              help="Run the agent crash-restart drill: a seeded "
                   "agent_restart schedule kills the agent process "
                   "under a running task — the revived agent "
                   "re-adopts it from the slot ledger (one start, "
                   "retries==0, adoption leg priced)")
@click.option("--serve-kill", is_flag=True, default=False,
              help="Run the serving replica-kill drill: a replica "
                   "dies SIGKILL-style under live token streams — "
                   "the router resumes every stream on the sibling, "
                   "exactly-once and byte-identical to a clean "
                   "greedy decode, serving_recovery leg priced")
@click.option("--serve-drain", is_flag=True, default=False,
              help="Run the serving replica-drain drill: a preempt "
                   "notice drains a replica through the full ladder "
                   "(healthz 503+marker, 503+Retry-After admissions, "
                   "cooperative-not-fault rotation, grace-deadline "
                   "abandons resumed on the sibling)")
@click.option("--serve-router", is_flag=True, default=False,
              help="Run the serving router-restart drill: the "
                   "router crashes mid-stream and clients cancel-"
                   "then-resume through a successor — the replicas' "
                   "duplicate gates keep delivery exactly-once")
@click.pass_context
def chaos_drill(click_ctx, seed, tasks, duration, kinds,
                injections_per_kind, preempt, victim, evict, resize,
                migrate, outage, partition, restart, serve_kill,
                serve_drain, serve_router):
    """Run the seeded drill on a local fakepod pool and assert the
    recovery invariants (nonzero exit = a self-healing regression)."""
    fleet.action_chaos_drill(
        None, seed, tasks=tasks, duration=duration,
        kinds=_parse_kinds(kinds),
        injections_per_kind=injections_per_kind,
        preempt=preempt, victim=victim, evict=evict, resize=resize,
        migrate=migrate, outage=outage, partition=partition,
        restart=restart, serve_kill=serve_kill,
        serve_drain=serve_drain, serve_router=serve_router,
        raw=click_ctx.obj["raw"])


# -------------------------------- sim ----------------------------------

@cli.group()
def sim():
    """Discrete-event fleet simulator (sim/): thousands of virtual
    nodes under the REAL scheduling policies (sched/policy.py) and
    the REAL goodput pricing engine — deterministic, zero wall-time
    sleeps, chaos schedules replayed in virtual time."""


@sim.command("run")
@click.option("--scenario", default="steady",
              help="Scenario name (see `shipyard sim scenarios`)")
@click.option("--policy", default="baseline",
              help="Policy bundle: baseline, affinity, victim_cost, "
                   "autoscale, or combined")
@click.option("--seed", type=int, default=0,
              help="Trace/schedule seed (same seed, same report)")
@click.option("--nodes", type=int, default=200,
              help="Virtual fleet width")
@click.option("--tasks", type=int, default=2000,
              help="Tasks in the arrival trace")
@click.pass_context
def sim_run(click_ctx, scenario, policy, seed, nodes, tasks):
    """Run one simulation and print its goodput report (byte-
    identical for the same seed/scenario/shape/policy)."""
    fleet.action_sim_run(
        None, scenario=scenario, policy=policy, seed=seed,
        nodes=nodes, tasks=tasks, raw=click_ctx.obj["raw"])


@sim.command("scenarios")
@click.pass_context
def sim_scenarios(click_ctx):
    """List the scenario registry and the policy bundles."""
    fleet.action_sim_scenarios(None, raw=click_ctx.obj["raw"])


@sim.command("compare")
@click.option("--scenario", default="steady",
              help="Scenario name (see `shipyard sim scenarios`)")
@click.option("--policies", default="",
              help="Comma-separated policy bundles (baseline is "
                   "always included); default: all")
@click.option("--seed", type=int, default=0,
              help="Trace/schedule seed (same seed, same report)")
@click.option("--nodes", type=int, default=200,
              help="Virtual fleet width")
@click.option("--tasks", type=int, default=2000,
              help="Tasks in the arrival trace")
@click.pass_context
def sim_compare(click_ctx, scenario, policies, seed, nodes, tasks):
    """Run one scenario under several policy bundles and print each
    policy's goodput delta vs baseline."""
    fleet.action_sim_compare(
        None, scenario=scenario,
        policies=_parse_kinds(policies), seed=seed, nodes=nodes,
        tasks=tasks, raw=click_ctx.obj["raw"])


# ------------------------------- data ----------------------------------

@cli.group()
def data():
    """Data movement and task file access."""


@data.command("stream")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--filename", default="stdout.txt")
@click.pass_context
def data_stream(click_ctx, job_id, task_id, filename):
    fleet.action_data_stream(_ctx(click_ctx), job_id, task_id, filename)


@data.group("files")
def data_files():
    """Task file access."""


@data_files.command("list")
@click.argument("job_id")
@click.argument("task_id")
@click.pass_context
def data_files_list(click_ctx, job_id, task_id):
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    ctx = _ctx(click_ctx)
    for name in jobs_mgr.list_task_files(ctx.store, ctx.pool.id,
                                         job_id, task_id):
        click.echo(name)


@data_files.command("get")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--dest", default=".")
@click.pass_context
def data_files_get(click_ctx, job_id, task_id, dest):
    """Download all of a task's uploaded files."""
    from batch_shipyard_tpu.data import movement
    from batch_shipyard_tpu.state import names as names_mod
    ctx = _ctx(click_ctx)
    prefix = names_mod.task_output_key(ctx.pool.id, job_id, task_id,
                                       "")
    count = movement.egress_from_storage(ctx.store,
                                         prefix.rstrip("/"), dest)
    click.echo(f"downloaded {count} files to {dest}")


@data.command("ingress")
@click.option("--ssh-private-key", default=None,
              help="Key for direct-to-node (shared fs) ingress")
@click.pass_context
def data_ingress(click_ctx, ssh_private_key):
    from batch_shipyard_tpu.data import movement
    from batch_shipyard_tpu.state import names as names_mod
    ctx = _ctx(click_ctx)
    node_logins = None
    ssh_username = "shipyard"
    if "pool" in ctx.configs:
        from batch_shipyard_tpu.pool import manager as pool_mgr
        node_logins = []
        for row in ctx.store.query_entities(
                names_mod.TABLE_NODES, partition_key=ctx.pool.id):
            if row.get("state") not in pool_mgr.READY_STATES:
                continue  # never shard onto booting/failed nodes
            ip = row.get("external_ip") or row.get("internal_ip")
            if ip:
                node_logins.append((row["_rk"], ip, 22))
        ssh_username = ctx.pool.ssh.username
    movement.ingress_data(ctx.store, ctx.global_settings,
                          pool_id=ctx.pool.id if "pool" in
                          ctx.configs else None,
                          node_logins=node_logins or None,
                          ssh_username=ssh_username,
                          ssh_private_key=ssh_private_key)


# ------------------------------- diag ----------------------------------

@cli.group()
def diag():
    """Diagnostics."""


@diag.command("perf")
@click.pass_context
def diag_perf(click_ctx):
    fleet.action_perf_events(_ctx(click_ctx), raw=click_ctx.obj["raw"])


@diag.group("logs")
def diag_logs():
    """Node log management."""


@diag_logs.command("upload")
@click.pass_context
def diag_logs_upload(click_ctx):
    """Ask every node to ship its logs to the object store."""
    count = fleet.action_diag_logs_upload(_ctx(click_ctx))
    click.echo(f"log upload requested on {count} nodes")


@diag.command("gantt")
@click.option("--output", default=None,
              help="PNG output path (requires matplotlib)")
@click.pass_context
def diag_gantt(click_ctx, output):
    """Render the pool's perf-event timeline."""
    from batch_shipyard_tpu.graph import perf_graph
    ctx = _ctx(click_ctx)
    click.echo(perf_graph.graph_data(ctx.store, ctx.pool.id, output))


# ------------------------------ account --------------------------------

@cli.group()
def account():
    """Account / environment information."""


@account.command("info")
@click.pass_context
def account_info(click_ctx):
    fleet.action_account_info(_ctx(click_ctx),
                              raw=click_ctx.obj["raw"])


@account.command("quota")
@click.option("--zone", default=None,
              help="Zone to inspect (default: credentials gcp.zone)")
@click.pass_context
def account_quota(click_ctx, zone):
    """TPU capacity/quota for a zone: offered accelerator types +
    project chip quota limits (reference `account quota` /
    `account images`, shipyard.py:1009-1078)."""
    from batch_shipyard_tpu.substrate import quota as quota_mod
    ctx = _ctx(click_ctx)
    if ctx.credentials.gcp is None:
        raise click.ClickException(
            "account quota requires credentials.gcp")
    zone = zone or ctx.credentials.gcp.zone
    if not zone:
        raise click.ClickException(
            "no zone: pass --zone or set credentials gcp.zone")
    client = quota_mod.TpuQuotaClient(ctx.credentials.gcp.project)
    fleet._emit(quota_mod.quota_report(client, zone),
                click_ctx.obj["raw"])


# ------------------------------ secrets --------------------------------

def _secret_io_params(click_ctx):
    return _ctx(click_ctx).secret_io


@cli.group()
def secrets():
    """Secret store management (the keyvault group analog)."""


@secrets.command("put")
@click.argument("secret_id")
@click.option("--value", default=None,
              help="Secret value; read from stdin when omitted so it "
                   "stays out of shell history")
@click.pass_context
def secrets_put(click_ctx, secret_id, value):
    """Store a value under a secret:// id (keyvault add analog)."""
    import sys as _sys

    from batch_shipyard_tpu.utils import secrets as secrets_mod
    if value is None:
        value = _sys.stdin.read().rstrip("\n")
    secrets_file, project = _secret_io_params(click_ctx)
    secrets_mod.store_secret(secret_id, value,
                             secrets_file=secrets_file,
                             project=project)
    click.echo(f"stored {secret_id}")


@secrets.command("get")
@click.argument("secret_id")
@click.pass_context
def secrets_get(click_ctx, secret_id):
    """Resolve and print a secret:// id."""
    from batch_shipyard_tpu.utils import secrets as secrets_mod
    secrets_file, project = _secret_io_params(click_ctx)
    click.echo(secrets_mod.resolve_secret(
        secret_id, secrets_file=secrets_file, project=project))


@secrets.command("store-credentials")
@click.argument("secret_id")
@click.pass_context
def secrets_store_credentials(click_ctx, secret_id):
    """Store the loaded credentials.yaml under one secret id (the
    reference keeps whole credential files in KeyVault)."""
    from batch_shipyard_tpu.utils import secrets as secrets_mod
    ctx = _ctx(click_ctx)
    raw = ctx.configs.get("credentials")
    if not raw:
        raise click.ClickException("no credentials config loaded")
    secrets_file, project = _secret_io_params(click_ctx)
    secrets_mod.store_credentials_config(
        secret_id, raw, secrets_file=secrets_file, project=project)
    click.echo(f"credentials stored at {secret_id}")


@secrets.command("fetch-credentials")
@click.argument("secret_id")
@click.option("--out", default=None,
              help="Write to this file instead of stdout")
@click.pass_context
def secrets_fetch_credentials(click_ctx, secret_id, out):
    """Fetch a credentials.yaml stored via store-credentials."""
    import yaml as _yaml

    from batch_shipyard_tpu.utils import secrets as secrets_mod
    secrets_file, project = _secret_io_params(click_ctx)
    data = secrets_mod.fetch_credentials_config(
        secret_id, secrets_file=secrets_file, project=project)
    text = _yaml.safe_dump(data, default_flow_style=False)
    if out:
        import os as _os

        # Credential material: never world-readable (matches
        # store_secret's 0o600 on the secrets file).
        with open(out, "w", encoding="utf-8",
                  opener=lambda p, f: _os.open(p, f, 0o600)) as fh:
            fh.write(text)
        click.echo(f"wrote {out}")
    else:
        click.echo(text)


# ------------------------------ storage --------------------------------

@cli.group()
def storage():
    """State store management."""


@storage.command("sas")
@click.argument("key")
@click.option("--method", default="GET",
              type=click.Choice(["GET", "PUT", "DELETE"]))
@click.option("--expires-seconds", type=float, default=3600.0)
@click.option("--prefix", "as_prefix", is_flag=True,
              help="Treat KEY as a prefix: sign every object under "
                   "it (GET only)")
@click.pass_context
def storage_sas(click_ctx, key, method, expires_seconds, as_prefix):
    """Mint time-limited signed URL(s) for an object or prefix —
    hand a task output or ingress prefix to a third party without
    sharing credentials (reference `storage sas create`,
    shipyard.py:1327; GCS V4 signed URLs here)."""
    ctx = _ctx(click_ctx)
    if as_prefix:
        if method != "GET":
            raise click.ClickException(
                "--prefix signing is GET-only (a PUT prefix would "
                "grant arbitrary-name writes)")
        keys = ctx.store.list_objects(prefix=key)
        if not keys:
            raise click.ClickException(
                f"no objects under prefix {key!r}")
    else:
        keys = [key]
    try:
        urls = {k: ctx.store.generate_signed_url(
            k, method=method, expires_seconds=expires_seconds)
            for k in keys}
    except NotImplementedError as exc:
        raise click.ClickException(str(exc))
    fleet._emit({"method": method,
                 "expires_seconds": expires_seconds,
                 "urls": urls}, click_ctx.obj["raw"])


@storage.command("clear")
@click.option("-y", "--yes", is_flag=True, default=False)
@click.pass_context
def storage_clear(click_ctx, yes):
    """Clear ALL framework state (containers/tables/queues analog)."""
    ctx = _ctx(click_ctx)
    if not yes and not click.confirm(
            "Clear ALL state in the configured store?"):
        raise click.Abort()
    ctx.store.clear()


# ------------------------------ monitor --------------------------------

@cli.group()
def monitor():
    """Monitoring resource (Prometheus/Grafana + heimdall)."""


@monitor.command("create")
@click.option("--output-dir", default="./monitoring",
              help="Where to generate the deployment bundle")
@click.option("--start", is_flag=True, default=False,
              help="docker compose up the bundle locally")
@click.pass_context
def monitor_create(click_ctx, output_dir, start):
    from batch_shipyard_tpu.monitor import provision
    ctx = _ctx(click_ctx)
    mon = ctx.configs.get("monitor", {}).get("monitoring", {})
    le = (mon.get("services", {}) or {}).get("lets_encrypt", {}) or {}
    from batch_shipyard_tpu.utils import secrets as secrets_mod
    mon_creds = (ctx.configs.get("credentials", {})
                 .get("credentials", {}).get("monitoring", {}) or {})
    password = (mon_creds.get("grafana_admin_password_secret_id")
                or mon_creds.get("grafana_admin_password")
                or "admin")
    if secrets_mod.is_secret_id(password):
        password = secrets_mod.resolve_secret(password)
    bundle = provision.generate_monitoring_bundle(
        output_dir,
        prometheus_port=mon.get("prometheus", {}).get("port", 9090),
        grafana_port=mon.get("grafana", {}).get("port", 3000),
        grafana_password=password,
        scrape_interval=mon.get("prometheus", {}).get(
            "scrape_interval_seconds", 15),
        additional_dashboards=mon.get("grafana", {}).get(
            "additional_dashboards"),
        lets_encrypt_fqdn=(le.get("fqdn")
                           if le.get("enabled") else None),
        lets_encrypt_staging=le.get("use_staging_environment", False))
    if start:
        provision.start_local(bundle)
    click.echo(f"monitoring bundle: {bundle}")


@monitor.command("create-vm")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.option("--vm-size", default="e2-standard-2")
@click.pass_context
def monitor_create_vm(click_ctx, project, zone, vm_size):
    """Provision a GCE VM running the monitoring bundle (reference
    `shipyard monitor create` provisions the monitoring VM).
    public_ip honors monitor.yaml monitoring.public_ip.enabled
    (default true)."""
    from batch_shipyard_tpu.monitor import provision
    ctx = _ctx(click_ctx)
    mon = ctx.configs.get("monitor", {}).get("monitoring", {})
    le = (mon.get("services", {}) or {}).get("lets_encrypt", {}) or {}
    ip = provision.provision_monitoring_vm(
        ctx.store, project, zone=zone, vm_size=vm_size,
        public_ip=(mon.get("public_ip", {}) or {}).get(
            "enabled", True),
        prometheus_port=mon.get("prometheus", {}).get("port", 9090),
        grafana_port=mon.get("grafana", {}).get("port", 3000),
        lets_encrypt_fqdn=(le.get("fqdn")
                           if le.get("enabled") else None),
        lets_encrypt_staging=le.get("use_staging_environment", False))
    click.echo(f"monitoring VM provisioned: {ip}")


@monitor.command("destroy-vm")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def monitor_destroy_vm(click_ctx, project, zone):
    from batch_shipyard_tpu.monitor import provision
    provision.destroy_monitoring_vm(_ctx(click_ctx).store, project,
                                    zone=zone)
    click.echo("monitoring VM destroyed")


@monitor.command("status")
@click.option("--project", default=None)
@click.option("--zone", default=None)
@click.pass_context
def monitor_status(click_ctx, project, zone):
    """Monitoring VM record + live instance status (reference
    `monitor status`)."""
    from batch_shipyard_tpu.monitor import provision
    fleet._emit(provision.monitoring_vm_status(
        _ctx(click_ctx).store, project, zone=zone),
        click_ctx.obj["raw"])


@monitor.command("suspend")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def monitor_suspend(click_ctx, project, zone):
    """Stop the monitoring VM in place (reference
    `monitor suspend`)."""
    from batch_shipyard_tpu.monitor import provision
    provision.suspend_monitoring_vm(_ctx(click_ctx).store, project,
                                    zone=zone)
    click.echo("monitoring VM suspended")


@monitor.command("start")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def monitor_start(click_ctx, project, zone):
    """Restart a suspended monitoring VM (reference
    `monitor start`)."""
    from batch_shipyard_tpu.monitor import provision
    provision.start_monitoring_vm(_ctx(click_ctx).store, project,
                                  zone=zone)
    click.echo("monitoring VM started")


@monitor.command("ssh")
@click.option("--username", default=None)
@click.option("--ssh-private-key", default=None)
@click.option("--command", "remote_command", default=None)
@click.option("--no-exec", is_flag=True,
              help="Print the ssh command instead of running it")
@click.pass_context
def monitor_ssh(click_ctx, username, ssh_private_key, remote_command,
                no_exec):
    """ssh into the monitoring VM (reference `monitor ssh`)."""
    import subprocess as _subprocess

    from batch_shipyard_tpu.monitor import provision
    argv = provision.monitoring_vm_ssh_argv(
        _ctx(click_ctx).store, username, ssh_private_key,
        command=remote_command)
    if no_exec:
        click.echo(" ".join(argv))
    else:
        raise SystemExit(_subprocess.call(argv))


@monitor.command("add")
@click.option("--pool-id", "pool_id", default=None)
@click.pass_context
def monitor_add(click_ctx, pool_id):
    """Register the pool for monitoring discovery."""
    from batch_shipyard_tpu.monitor import heimdall
    ctx = _ctx(click_ctx)
    pool = ctx.pool
    heimdall.add_pool_to_monitor(
        ctx.store, pool_id or pool.id,
        node_exporter_port=pool.node_exporter.port,
        cadvisor_port=(pool.cadvisor.port if pool.cadvisor.enabled
                       else None))


@monitor.command("remove")
@click.argument("resource_key")
@click.pass_context
def monitor_remove(click_ctx, resource_key):
    from batch_shipyard_tpu.monitor import heimdall
    heimdall.remove_resource_from_monitor(_ctx(click_ctx).store,
                                          resource_key)


@monitor.command("list")
@click.pass_context
def monitor_list(click_ctx):
    from batch_shipyard_tpu.monitor import heimdall
    fleet._emit({"resources": heimdall.list_monitored_resources(
        _ctx(click_ctx).store)}, click_ctx.obj["raw"])


@monitor.command("heimdall")
@click.option("--output-dir", default="./monitoring/file_sd")
@click.option("--once", is_flag=True, default=False)
@click.option("--poll-interval", type=float, default=None,
              help="Default: monitor.yaml services."
                   "resource_polling_interval_seconds (15)")
@click.pass_context
def monitor_heimdall(click_ctx, output_dir, once, poll_interval):
    """Run the service-discovery daemon (writes prometheus file_sd)."""
    from batch_shipyard_tpu.monitor import heimdall
    ctx = _ctx(click_ctx)
    if poll_interval is None:
        poll_interval = float(
            ctx.configs.get("monitor", {}).get("monitoring", {})
            .get("services", {})
            .get("resource_polling_interval_seconds", 15))
    if once:
        click.echo(heimdall.write_file_sd(ctx.store, output_dir))
        click.echo(heimdall.write_goodput_metrics(ctx.store,
                                                  output_dir))
    else:
        heimdall.run_daemon(ctx.store, output_dir, poll_interval)


# -------------------------------- fed ----------------------------------

@cli.group()
def fed():
    """Heterogeneous-pool federation."""


@fed.command("create")
@click.argument("federation_id")
@click.option("--force", is_flag=True, default=False)
@click.pass_context
def fed_create(click_ctx, federation_id, force):
    from batch_shipyard_tpu.federation import federation as fed_mod
    fed_mod.create_federation(_ctx(click_ctx).store, federation_id,
                              force=force)


@fed.command("destroy")
@click.argument("federation_id")
@click.pass_context
def fed_destroy(click_ctx, federation_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    fed_mod.destroy_federation(_ctx(click_ctx).store, federation_id)


@fed.command("list")
@click.pass_context
def fed_list(click_ctx):
    from batch_shipyard_tpu.federation import federation as fed_mod
    fleet._emit({"federations": fed_mod.list_federations(
        _ctx(click_ctx).store)}, click_ctx.obj["raw"])


@fed.group("pool")
def fed_pool():
    """Federation pool membership."""


@fed_pool.command("add")
@click.argument("federation_id")
@click.option("--pool-id", default=None)
@click.pass_context
def fed_pool_add(click_ctx, federation_id, pool_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    ctx = _ctx(click_ctx)
    fed_mod.add_pool_to_federation(ctx.store, federation_id,
                                   pool_id or ctx.pool.id)


@fed_pool.command("remove")
@click.argument("federation_id")
@click.option("--pool-id", default=None)
@click.pass_context
def fed_pool_remove(click_ctx, federation_id, pool_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    ctx = _ctx(click_ctx)
    fed_mod.remove_pool_from_federation(ctx.store, federation_id,
                                        pool_id or ctx.pool.id)


@fed.group("jobs")
def fed_jobs():
    """Federated job submission."""


@fed_jobs.command("add")
@click.argument("federation_id")
@click.pass_context
def fed_jobs_add(click_ctx, federation_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    ctx = _ctx(click_ctx)
    action = fed_mod.submit_job_to_federation(
        ctx.store, federation_id, ctx.configs["jobs"])
    click.echo(f"submitted action {action}")


@fed_jobs.command("list")
@click.argument("federation_id")
@click.pass_context
def fed_jobs_list(click_ctx, federation_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    fleet._emit({"jobs": fed_mod.list_federation_jobs(
        _ctx(click_ctx).store, federation_id)}, click_ctx.obj["raw"])


@fed_jobs.command("term")
@click.argument("federation_id")
@click.argument("job_id")
@click.pass_context
def fed_jobs_term(click_ctx, federation_id, job_id):
    """Terminate a federated job on whichever pool it landed on."""
    from batch_shipyard_tpu.federation import federation as fed_mod
    pool_id = fed_mod.terminate_federation_job(
        _ctx(click_ctx).store, federation_id, job_id)
    click.echo(f"terminated {job_id} on pool {pool_id}")


@fed_jobs.command("del")
@click.argument("federation_id")
@click.argument("job_id")
@click.pass_context
def fed_jobs_del(click_ctx, federation_id, job_id):
    """Delete a federated job on whichever pool it landed on."""
    from batch_shipyard_tpu.federation import federation as fed_mod
    pool_id = fed_mod.delete_federation_job(
        _ctx(click_ctx).store, federation_id, job_id)
    click.echo(f"deleted {job_id} from pool {pool_id}")


@fed_jobs.command("zap")
@click.argument("federation_id")
@click.argument("action_id")
@click.pass_context
def fed_jobs_zap(click_ctx, federation_id, action_id):
    from batch_shipyard_tpu.federation import federation as fed_mod
    fed_mod.zap_action(_ctx(click_ctx).store, federation_id, action_id)


@fed_jobs.command("gc")
@click.argument("federation_id")
@click.pass_context
def fed_jobs_gc(click_ctx, federation_id):
    """Remove stale job-location rows (jobs deleted behind the
    federation's back)."""
    from batch_shipyard_tpu.federation import federation as fed_mod
    removed = fed_mod.gc_federation_jobs(
        _ctx(click_ctx).store, federation_id)
    fleet._emit({"removed": removed}, click_ctx.obj["raw"])


@fed.command("create-vm")
@click.argument("federation_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.option("--replicas", type=int, default=1,
              help="Proxy replicas (store lease elects the active)")
@click.option("--package-source", default="batch-shipyard-tpu")
@click.pass_context
def fed_create_vm(click_ctx, federation_id, project, zone, replicas,
                  package_source):
    """Provision federation proxy VM(s) running the processor."""
    import yaml as _yaml

    from batch_shipyard_tpu.federation import provision as fed_prov
    ctx = _ctx(click_ctx)
    store_config = _yaml.safe_dump(ctx.configs.get("credentials", {}))
    fed_conf = ctx.configs.get("federation", {}).get("federation",
                                                     {}) or {}
    for replica in range(replicas):
        ip = fed_prov.provision_proxy_vm(
            ctx.store, federation_id, project, zone=zone,
            replica=replica, package_source=package_source,
            store_config_yaml=store_config,
            public_ip=(fed_conf.get("public_ip", {}) or {}).get(
                "enabled", True))
        click.echo(f"proxy{replica}: {ip}")


@fed.command("destroy-vm")
@click.argument("federation_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fed_destroy_vm(click_ctx, federation_id, project, zone):
    from batch_shipyard_tpu.federation import provision as fed_prov
    count = fed_prov.destroy_proxy_vms(
        _ctx(click_ctx).store, federation_id, project, zone=zone)
    click.echo(f"destroyed {count} proxy VM(s)")


@fed.group("proxy", invoke_without_command=True)
@click.option("--poll-interval", type=float, default=None,
              help="Default: federation.yaml proxy_options."
                   "polling_interval (1.0)")
@click.pass_context
def fed_proxy(click_ctx, poll_interval):
    """Run the federation scheduler daemon (bare invocation), or
    manage proxy VMs (ssh/suspend/start/status subcommands)."""
    if click_ctx.invoked_subcommand is not None:
        return
    import logging as logging_mod
    import time as time_mod

    from batch_shipyard_tpu.federation import federation as fed_mod
    from batch_shipyard_tpu.utils import util as util_mod
    ctx = _ctx(click_ctx)
    opts = (ctx.configs.get("federation", {}).get("federation", {})
            .get("proxy_options", {}) or {})
    # proxy_options.logging: honored, not just validated (reference
    # federation.yaml logging block). The file handler reuses the
    # framework's UTC format so fed-proxy.log correlates with stderr.
    log_conf = opts.get("logging", {}) or {}
    logger = logging_mod.getLogger("batch_shipyard_tpu")
    if log_conf.get("level"):
        logger.setLevel(log_conf["level"].upper())
    if log_conf.get("persistence"):
        handler = logging_mod.FileHandler("fed-proxy.log",
                                          encoding="utf-8")
        formatter = logging_mod.Formatter(
            fmt=util_mod._LOGGER_FORMAT,
            datefmt=util_mod._LOGGER_DATEFMT)
        formatter.converter = time_mod.gmtime
        handler.setFormatter(formatter)
        logger.addHandler(handler)
    if poll_interval is None:
        # Schema shape is a map ({federations, actions} seconds —
        # reference federation.yaml); the ACTIONS cadence drives the
        # processor loop.
        pi_conf = opts.get("polling_interval") or {}
        poll_interval = float(pi_conf.get("actions", 1.0))
    sched = opts.get("scheduling", {}) or {}
    proc = fed_mod.FederationProcessor(
        ctx.store, poll_interval=poll_interval,
        after_success_blackout=float(
            sched.get("after_success_blackout_interval", 0.0)))
    proc.run()


@fed_proxy.command("status")
@click.argument("federation_id")
@click.option("--project", default=None)
@click.option("--zone", default=None)
@click.pass_context
def fed_proxy_status(click_ctx, federation_id, project, zone):
    """Proxy VM records + live status (reference
    `fed proxy status`)."""
    from batch_shipyard_tpu.federation import provision as fed_prov
    fleet._emit({"proxies": fed_prov.proxy_vm_status(
        _ctx(click_ctx).store, federation_id, project, zone=zone)},
        click_ctx.obj["raw"])


@fed_proxy.command("suspend")
@click.argument("federation_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.option("--replica", type=int, default=None,
              help="Suspend one replica (default: all)")
@click.pass_context
def fed_proxy_suspend(click_ctx, federation_id, project, zone,
                      replica):
    """Stop proxy VM(s) in place (reference `fed proxy suspend`)."""
    from batch_shipyard_tpu.federation import provision as fed_prov
    count = fed_prov.suspend_proxy_vms(
        _ctx(click_ctx).store, federation_id, project, zone=zone,
        replica=replica)
    click.echo(f"suspended {count} proxy VM(s)")


@fed_proxy.command("start")
@click.argument("federation_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.option("--replica", type=int, default=None,
              help="Start one replica (default: all)")
@click.pass_context
def fed_proxy_start(click_ctx, federation_id, project, zone, replica):
    """Restart suspended proxy VM(s) (reference
    `fed proxy start`)."""
    from batch_shipyard_tpu.federation import provision as fed_prov
    count = fed_prov.start_proxy_vms(
        _ctx(click_ctx).store, federation_id, project, zone=zone,
        replica=replica)
    click.echo(f"started {count} proxy VM(s)")


@fed_proxy.command("ssh")
@click.argument("federation_id")
@click.option("--replica", type=int, default=0)
@click.option("--username", default=None)
@click.option("--ssh-private-key", default=None)
@click.option("--command", "remote_command", default=None)
@click.option("--no-exec", is_flag=True,
              help="Print the ssh command instead of running it")
@click.pass_context
def fed_proxy_ssh(click_ctx, federation_id, replica, username,
                  ssh_private_key, remote_command, no_exec):
    """ssh into a proxy VM replica (reference `fed proxy ssh`)."""
    import subprocess as _subprocess

    from batch_shipyard_tpu.federation import provision as fed_prov
    argv = fed_prov.proxy_vm_ssh_argv(
        _ctx(click_ctx).store, federation_id, replica=replica,
        username=username, ssh_private_key=ssh_private_key,
        command=remote_command)
    if no_exec:
        click.echo(" ".join(argv))
    else:
        raise SystemExit(_subprocess.call(argv))


# ------------------------------- slurm ---------------------------------

@cli.group()
def slurm():
    """Slurm elastic burst."""


@slurm.command("conf")
@click.pass_context
def slurm_conf(click_ctx):
    """Generate slurm.conf for the configured elastic partitions."""
    from batch_shipyard_tpu.slurm import burst
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    opts = sconf.get("slurm_options", {}) or {}
    click.echo(burst.generate_slurm_conf(
        cluster_id, opts.get("elastic_partitions", {}),
        idle_reclaim_seconds=opts.get(
            "idle_reclaim_time_seconds", 300),
        unmanaged_partitions=opts.get("unmanaged_partitions", ())))


@slurm.command("resume")
@click.argument("hostlist")
@click.pass_context
def slurm_resume(click_ctx, hostlist):
    """Slurm ResumeProgram entry: bind hosts to pool nodes."""
    from batch_shipyard_tpu.slurm import burst
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    hosts = burst.expand_hostlist(hostlist)
    partition = hosts[0].rsplit("-", 1)[0] if hosts else "default"
    assignments = burst.process_resume(
        ctx.store, ctx.substrate(), ctx.pool, cluster_id, partition,
        hosts)
    fleet._emit({"assignments": assignments}, click_ctx.obj["raw"])


@slurm.command("publish-munge-key")
@click.option("--cluster-id", required=True)
@click.option("--key-file", required=True)
@click.pass_context
def slurm_publish_munge_key(click_ctx, cluster_id, key_file):
    """Controller-side: publish the munge key through the store."""
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    with open(key_file, "rb") as fh:
        slurm_prov.publish_munge_key(_ctx(click_ctx).store,
                                     cluster_id, fh.read())
    click.echo("munge key published")


@slurm.command("fetch-munge-key")
@click.option("--cluster-id", required=True)
@click.option("--key-file", required=True)
@click.option("--timeout", type=float, default=600.0)
@click.pass_context
def slurm_fetch_munge_key(click_ctx, cluster_id, key_file, timeout):
    """Node-side: poll the store for the controller's munge key."""
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    data = slurm_prov.fetch_munge_key(_ctx(click_ctx).store,
                                      cluster_id, timeout=timeout)
    with open(key_file, "wb") as fh:
        fh.write(data)
    click.echo("munge key fetched")


@slurm.command("cluster-create")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.option("--db-password", default="shipyard")
@click.option("--login-count", type=int, default=0)
@click.option("--package-source", default="batch-shipyard-tpu",
              help="pip requirement or gs:// wheel the VMs install")
@click.pass_context
def slurm_cluster_create(click_ctx, project, zone, db_password,
                         login_count, package_source):
    """Provision the slurm control plane (controller + logins)."""
    import yaml as _yaml

    from batch_shipyard_tpu.slurm import burst
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    opts = sconf.get("slurm_options", {}) or {}
    partitions = opts.get("elastic_partitions", {})
    # The VMs reach the same state store this CLI uses: ship our
    # credentials config into their bootstrap.
    store_config = _yaml.safe_dump(ctx.configs.get("credentials", {}))
    record = slurm_prov.create_slurm_cluster(
        ctx.store, cluster_id,
        burst.generate_slurm_conf(
            cluster_id, partitions,
            idle_reclaim_seconds=opts.get(
                "idle_reclaim_time_seconds", 300),
            unmanaged_partitions=opts.get(
                "unmanaged_partitions", ())),
        db_password, project, zone=zone, login_count=login_count,
        package_source=package_source,
        store_config_yaml=store_config,
        public_ip=(sconf.get("controller", {}) or {}).get(
            "public_ip", {}).get("enabled", True))
    fleet._emit(record, click_ctx.obj["raw"])


@slurm.command("cluster-destroy")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def slurm_cluster_destroy(click_ctx, project, zone):
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    slurm_prov.destroy_slurm_cluster(ctx.store, cluster_id, project,
                                     zone=zone)
    click.echo(f"slurm cluster {cluster_id} destroyed")


@slurm.command("cluster-status")
@click.option("--project", default=None)
@click.option("--zone", default=None)
@click.pass_context
def slurm_cluster_status(click_ctx, project, zone):
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    fleet._emit(slurm_prov.slurm_cluster_status(
        ctx.store, cluster_id, project=project, zone=zone),
        click_ctx.obj["raw"])


@slurm.command("cluster-suspend")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def slurm_cluster_suspend(click_ctx, project, zone):
    """Stop the controller + login VMs in place (reference
    `slurm cluster suspend`; compute nodes are pool slices — use
    `pool suspend` for those)."""
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    cluster_id = ctx.configs.get("slurm", {}).get("slurm", {}).get(
        "cluster_id", "shipyard")
    stopped = slurm_prov.suspend_slurm_cluster(
        ctx.store, cluster_id, project=project, zone=zone)
    click.echo(f"suspended: {', '.join(stopped)}")


@slurm.command("cluster-start")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def slurm_cluster_start(click_ctx, project, zone):
    """Restart suspended control-plane VMs (reference
    `slurm cluster start`)."""
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    cluster_id = ctx.configs.get("slurm", {}).get("slurm", {}).get(
        "cluster_id", "shipyard")
    started = slurm_prov.start_slurm_cluster(
        ctx.store, cluster_id, project=project, zone=zone)
    click.echo(f"started: {', '.join(started)}")


@slurm.command("ssh")
@click.argument("target",
                type=click.Choice(["controller", "login", "node"]))
@click.option("--index", type=int, default=0,
              help="Login VM index (target=login)")
@click.option("--partition", default=None,
              help="Slurm partition (target=node)")
@click.option("--host", default=None,
              help="Slurm hostname (target=node)")
@click.option("--username", default=None)
@click.option("--ssh-private-key", default=None)
@click.option("--command", "remote_command", default=None)
@click.option("--no-exec", is_flag=True,
              help="Print the ssh command instead of running it")
@click.pass_context
def slurm_ssh(click_ctx, target, index, partition, host, username,
              ssh_private_key, remote_command, no_exec):
    """ssh into the controller, a login VM, or a compute node
    (reference `slurm ssh controller|login|node`)."""
    import subprocess as _subprocess

    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    cluster_id = ctx.configs.get("slurm", {}).get("slurm", {}).get(
        "cluster_id", "shipyard")
    argv = slurm_prov.slurm_ssh_argv(
        ctx.store, cluster_id, target=target, index=index,
        partition=partition, host=host, username=username,
        ssh_private_key=ssh_private_key, command=remote_command)
    if no_exec:
        click.echo(" ".join(argv))
    else:
        raise SystemExit(_subprocess.call(argv))


@slurm.command("join-script")
@click.pass_context
def slurm_join_script(click_ctx):
    """Emit the compute-node slurmd join script."""
    from batch_shipyard_tpu.slurm import burst
    from batch_shipyard_tpu.slurm import provision as slurm_prov
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    partitions = sconf.get("slurm_options", {}).get(
        "elastic_partitions", {})
    click.echo(slurm_prov.generate_compute_join_script(
        cluster_id,
        burst.generate_slurm_conf(cluster_id, partitions)))


@slurm.command("suspend")
@click.argument("hostlist")
@click.pass_context
def slurm_suspend(click_ctx, hostlist):
    """Slurm SuspendProgram entry: release host bindings."""
    from batch_shipyard_tpu.slurm import burst
    ctx = _ctx(click_ctx)
    sconf = ctx.configs.get("slurm", {}).get("slurm", {})
    cluster_id = sconf.get("cluster_id", "shipyard")
    hosts = burst.expand_hostlist(hostlist)
    partition = hosts[0].rsplit("-", 1)[0] if hosts else "default"
    released = burst.process_suspend(
        ctx.store, ctx.substrate(), ctx.pool, cluster_id, partition,
        hosts)
    click.echo(f"released {released} hosts")


# --------------------------------- fs ----------------------------------

@cli.group()
def fs():
    """Remote filesystem clusters."""


@fs.group("cluster")
def fs_cluster():
    """Storage cluster lifecycle."""


@fs.group("bucket")
def fs_bucket():
    """Serverless GCS-FUSE shared storage (fs.yaml gcs_buckets)."""


@fs_bucket.command("mount-args")
@click.argument("name")
@click.pass_context
def fs_bucket_mount_args(click_ctx, name):
    """Render the nodeprep mount command for a configured bucket."""
    from batch_shipyard_tpu.remotefs import manager as remotefs
    ctx = _ctx(click_ctx)
    for line in remotefs.gcs_bucket_mount_commands(
            ctx.configs.get("fs", {}), name):
        click.echo(line)


@fs_cluster.command("add")
@click.argument("cluster_id")
@click.option("--disk-count", type=int, default=None)
@click.option("--disk-size-gb", type=int, default=None)
@click.option("--vm-size", default=None)
@click.pass_context
def fs_cluster_add(click_ctx, cluster_id, disk_count, disk_size_gb,
                   vm_size):
    """Register a storage cluster. Defaults come from fs.yaml's
    remote_fs.storage_clusters.<cluster_id> block (the reference's
    config-driven `fs cluster add` flow); CLI options override."""
    from batch_shipyard_tpu.remotefs import manager as remotefs
    ctx = _ctx(click_ctx)
    remote_fs = (ctx.configs.get("fs", {}).get("remote_fs", {})
                 or {})
    spec = (remote_fs.get("storage_clusters", {}) or {}).get(
        cluster_id, {})
    disks = remote_fs.get("managed_disks", {}) or {}
    remotefs.create_storage_cluster_record(
        ctx.store, cluster_id,
        disk_count=disk_count if disk_count is not None else
        int(spec.get("disk_count", 2)),
        disk_size_gb=disk_size_gb if disk_size_gb is not None else
        int(spec.get("disk_size_gb",
                     disks.get("disk_size_gb", 256))),
        disk_type=spec.get("disk_type",
                           disks.get("disk_type", "pd-ssd")),
        vm_size=vm_size or spec.get("vm_size", "n2-standard-8"))


@fs_cluster.command("del")
@click.argument("cluster_id")
@click.pass_context
def fs_cluster_del(click_ctx, cluster_id):
    from batch_shipyard_tpu.remotefs import manager as remotefs
    remotefs.delete_storage_cluster(_ctx(click_ctx).store, cluster_id)


@fs_cluster.command("mount-args")
@click.argument("cluster_id")
@click.pass_context
def fs_cluster_mount_args(click_ctx, cluster_id):
    from batch_shipyard_tpu.remotefs import manager as remotefs
    for line in remotefs.create_storage_cluster_mount_args(
            _ctx(click_ctx).store, cluster_id):
        click.echo(line)


@fs_cluster.command("provision")
@click.argument("cluster_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_provision(click_ctx, cluster_id, project, zone):
    """Create the NFS server VM + striped data disks."""
    from batch_shipyard_tpu.remotefs import manager as remotefs
    remotefs.provision_nfs_server(_ctx(click_ctx).store, cluster_id,
                                  project, zone=zone)
    click.echo(f"storage cluster {cluster_id} provisioned")


@fs_cluster.command("suspend")
@click.argument("cluster_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_suspend(click_ctx, cluster_id, project, zone):
    from batch_shipyard_tpu.remotefs import manager as remotefs
    remotefs.suspend_storage_cluster(_ctx(click_ctx).store,
                                     cluster_id, project, zone=zone)
    click.echo(f"storage cluster {cluster_id} suspended")


@fs_cluster.command("start")
@click.argument("cluster_id")
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_start(click_ctx, cluster_id, project, zone):
    from batch_shipyard_tpu.remotefs import manager as remotefs
    remotefs.start_storage_cluster(_ctx(click_ctx).store, cluster_id,
                                   project, zone=zone)
    click.echo(f"storage cluster {cluster_id} started")


@fs_cluster.command("status")
@click.argument("cluster_id")
@click.option("--project", default=None)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_status(click_ctx, cluster_id, project, zone):
    from batch_shipyard_tpu.remotefs import manager as remotefs
    fleet._emit(remotefs.storage_cluster_status(
        _ctx(click_ctx).store, cluster_id, project=project,
        zone=zone), click_ctx.obj["raw"])


@fs_cluster.command("resize")
@click.argument("cluster_id")
@click.option("--vm-size", required=True)
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_resize(click_ctx, cluster_id, vm_size, project, zone):
    """Change the server's machine type (stop -> resize -> start)."""
    from batch_shipyard_tpu.remotefs import manager as remotefs
    remotefs.resize_storage_cluster(_ctx(click_ctx).store, cluster_id,
                                    vm_size, project, zone=zone)
    click.echo(f"storage cluster {cluster_id} resized to {vm_size}")


@fs_cluster.command("expand")
@click.argument("cluster_id")
@click.option("--additional-disks", type=int, required=True)
@click.option("--project", required=True)
@click.option("--zone", default=None)
@click.pass_context
def fs_cluster_expand(click_ctx, cluster_id, additional_disks,
                      project, zone):
    """Attach new striped disks; prints the on-server grow script."""
    from batch_shipyard_tpu.remotefs import manager as remotefs
    click.echo(remotefs.expand_storage_cluster_live(
        _ctx(click_ctx).store, cluster_id, additional_disks, project,
        zone=zone))


# -------------------------------- misc ---------------------------------

@cli.group()
def misc():
    """Miscellaneous utilities."""


@misc.command("tunnel")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--remote-port", type=int, required=True,
              help="Port the task's service listens on (e.g. the "
                   "serving front end)")
@click.option("--local-port", type=int, default=None)
@click.option("--ssh-private-key", default=None)
@click.option("--output-dir", default=".")
@click.pass_context
def misc_tunnel(click_ctx, job_id, task_id, remote_port, local_port,
                ssh_private_key, output_dir):
    """Write an ssh port-forward script to a task's service port."""
    from batch_shipyard_tpu.utils import misc as misc_mod
    ctx = _ctx(click_ctx)
    plan = misc_mod.plan_port_tunnel(
        ctx.store, ctx.substrate(), ctx.pool.id, job_id, task_id,
        remote_port, local_port=local_port,
        ssh_private_key=ssh_private_key, output_dir=output_dir)
    fleet._emit(plan, click_ctx.obj["raw"])


@misc.command("tensorboard")
@click.argument("job_id")
@click.argument("task_id")
@click.option("--logdir", default=None)
@click.option("--local-port", type=int, default=16006)
@click.option("--plan-only", is_flag=True, default=False,
              help="Emit the plan without starting anything")
@click.pass_context
def misc_tensorboard(click_ctx, job_id, task_id, logdir, local_port,
                     plan_only):
    """Start TensorBoard on a task's node + the local ssh tunnel."""
    from batch_shipyard_tpu.utils import misc as misc_mod
    ctx = _ctx(click_ctx)
    if plan_only:
        plan = misc_mod.plan_tensorboard_tunnel(
            ctx.store, ctx.substrate(), ctx.pool.id, job_id, task_id,
            logdir=logdir, local_port=local_port)
        fleet._emit(plan, click_ctx.obj["raw"])
        return
    misc_mod.tunnel_tensorboard(
        ctx.store, ctx.substrate(), ctx.pool.id, job_id, task_id,
        logdir=logdir, local_port=local_port)


@misc.command("mirror-images")
@click.argument("dest_registry")
@click.option("--dry-run", is_flag=True, default=False)
@click.pass_context
def misc_mirror_images(click_ctx, dest_registry, dry_run):
    """Mirror the global-resource images into a private registry."""
    from batch_shipyard_tpu.utils import misc as misc_mod
    ctx = _ctx(click_ctx)
    images = list(ctx.global_settings.docker_images)
    targets = misc_mod.mirror_images(images, dest_registry,
                                     dry_run=dry_run)
    for t in targets:
        click.echo(t)


def main():
    return cli(prog_name="shipyard-tpu")


if __name__ == "__main__":
    sys.exit(main())

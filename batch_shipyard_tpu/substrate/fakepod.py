"""FakePod substrate: in-process TPU pod simulator for tests.

Every node of every slice is a thread running the REAL NodeAgent against
the shared state store; 'runtime: none' tasks execute as real
subprocesses, so pool/job/task lifecycle, gang rendezvous, retries, and
recovery paths are exercised end-to-end in unit tests — the test
substrate SURVEY.md section 4 says the reference lacks and we must add.

Failure injection (for the recovery tests the reference does with live
Azure): FakePodSubstrate.inject maps node ids to failure modes:
  'nodeprep_fail_once'  -> start task fails on first boot, succeeds on
                           reboot (tests reboot_on_start_task_failed)
  'nodeprep_fail'       -> start task always fails
  'unusable'            -> node comes up unusable (tests
                           attempt_recovery_on_unusable)
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Optional

from batch_shipyard_tpu.agent.node_agent import NodeAgent, NodeIdentity
from batch_shipyard_tpu.config.settings import PoolSettings
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.substrate import base
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


class FakePodSubstrate(base.ComputeSubstrate):
    def __init__(self, store: StateStore, work_root: Optional[str] = None,
                 nodeprep_delay: float = 0.0,
                 heartbeat_interval: float = 0.5,
                 node_stale_seconds: float = 30.0) -> None:
        self.store = store
        self.work_root = work_root or tempfile.mkdtemp(prefix="fakepod-")
        self.nodeprep_delay = nodeprep_delay
        self.heartbeat_interval = heartbeat_interval
        self.node_stale_seconds = node_stale_seconds
        # node_id -> failure mode
        self.inject: dict[str, str] = {}
        # Extra NodeAgent kwargs (scratch mount/export runners,
        # force_remote_scratch, ...) for fault-injection tests.
        self.agent_kwargs: dict = {}
        self._agents: dict[str, dict[str, NodeAgent]] = {}
        self._boot_threads: dict[str, threading.Thread] = {}
        self._boot_counts: dict[str, int] = {}
        self._lock = threading.Lock()

    # --------------------------- internals -----------------------------

    @staticmethod
    def node_id(pool_id: str, slice_index: int, worker_index: int) -> str:
        return f"{pool_id}-s{slice_index}-w{worker_index}"

    def _nodeprep(self, agent: NodeAgent) -> None:
        node_id = agent.identity.node_id
        with self._lock:
            self._boot_counts[node_id] = self._boot_counts.get(
                node_id, 0) + 1
            boots = self._boot_counts[node_id]
        if self.nodeprep_delay:
            import time
            time.sleep(self.nodeprep_delay)
        mode = self.inject.get(node_id)
        if mode == "nodeprep_fail":
            raise RuntimeError("injected nodeprep failure")
        if mode == "nodeprep_fail_once" and boots == 1:
            raise RuntimeError("injected one-shot nodeprep failure")
        if mode == "unusable":
            # Mimic a node that finishes start task but is broken.
            from batch_shipyard_tpu.agent.node_agent import (
                NodeUnusableError)
            raise NodeUnusableError("injected unusable")

    def _spawn_agent(self, pool: PoolSettings, slice_index: int,
                     worker_index: int, node_index: int) -> None:
        node_id = self.node_id(pool.id, slice_index, worker_index)
        identity = NodeIdentity(
            pool_id=pool.id, node_id=node_id, node_index=node_index,
            hostname=node_id,
            internal_ip=f"10.{slice_index}.{worker_index // 256}."
                        f"{worker_index % 256 + 1}",
            slice_index=slice_index, worker_index=worker_index)
        kwargs = {
            "heartbeat_interval": self.heartbeat_interval,
            "poll_interval": 0.05, "gang_timeout": 60.0,
            "job_state_ttl": 0.2,
            "node_stale_seconds": self.node_stale_seconds,
            "nodeprep": self._nodeprep, "substrate": self,
        }
        # agent_kwargs may override ANY default (tests shrink
        # gang_timeout/claim_visibility; drills tighten backoff).
        kwargs.update(self.agent_kwargs)
        agent = NodeAgent(
            self.store, identity, pool,
            work_dir=os.path.join(self.work_root, pool.id, node_id),
            **kwargs)
        import time as time_mod
        self.store.upsert_entity(
            names.TABLE_NODES, pool.id, node_id, {
                "state": "creating", "hostname": identity.hostname,
                "internal_ip": identity.internal_ip,
                "node_index": node_index, "slice_index": slice_index,
                "worker_index": worker_index,
                # Registration grace anchor: _node_alive treats a
                # never-heartbeated node as alive while this is fresh
                # (the gang-observer startup race fix).
                "registered_at": time_mod.time()})
        thread = threading.Thread(
            target=self._boot_agent, args=(agent,),
            name=f"fakepod-boot-{node_id}", daemon=True)
        # Agent + boot thread register atomically so teardown always
        # sees (and joins) the boot thread of any agent it stops.
        with self._lock:
            self._agents.setdefault(pool.id, {})[node_id] = agent
            self._boot_threads[node_id] = thread
        thread.start()

    def _boot_agent(self, agent: NodeAgent) -> None:
        try:
            agent.start()
        except Exception:
            logger.exception("fake node crashed during boot")

    def _pool_shape(self, pool: PoolSettings) -> tuple[int, int]:
        """(num_slices, workers_per_slice)."""
        if pool.tpu is not None:
            return pool.tpu.num_slices, pool.tpu.workers_per_slice
        return 1, pool.vm_count_dedicated + pool.vm_count_low_priority

    # --------------------------- interface -----------------------------

    def allocate_pool(self, pool: PoolSettings) -> None:
        num_slices, workers = self._pool_shape(pool)
        node_index = 0
        for s in range(num_slices):
            for w in range(workers):
                self._spawn_agent(pool, s, w, node_index)
                node_index += 1

    def deallocate_pool(self, pool_id: str) -> None:
        with self._lock:
            agents = self._agents.pop(pool_id, {})
        for agent in agents.values():
            agent.stop()
        for agent in agents.values():
            with self._lock:
                boot = self._boot_threads.pop(
                    agent.identity.node_id, None)
            if boot is not None:
                boot.join(timeout=10.0)
            agent.join(timeout=5.0)
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool_id)):
            self.store.delete_entity(names.TABLE_NODES, pool_id, row["_rk"])

    def resize_pool(self, pool: PoolSettings, num_slices: int) -> None:
        """TPU pools: num_slices is a slice count (slice-atomic);
        non-TPU pools: num_slices is a node count."""
        if pool.tpu is None:
            self._resize_nodes(pool, num_slices)
            return
        current = sorted({
            int(row["slice_index"]) for row in self.store.query_entities(
                names.TABLE_NODES, partition_key=pool.id)})
        have = len(current)
        _, workers = self._pool_shape(pool)
        if num_slices > have:
            base_index = have * workers
            for s in range(have, num_slices):
                for w in range(workers):
                    self._spawn_agent(pool, s, w, base_index)
                    base_index += 1
        elif num_slices < have:
            for s in current[num_slices:]:
                self._teardown_slice(pool.id, s)

    def _resize_nodes(self, pool: PoolSettings, num_nodes: int) -> None:
        rows = sorted(self.store.query_entities(
            names.TABLE_NODES, partition_key=pool.id),
            key=lambda r: int(r.get("node_index", 0)))
        have = len(rows)
        if num_nodes > have:
            for idx in range(have, num_nodes):
                self._spawn_agent(pool, 0, idx, idx)
        elif num_nodes < have:
            for row in rows[num_nodes:]:
                node_id = row["_rk"]
                with self._lock:
                    agent = self._agents.get(pool.id, {}).pop(
                        node_id, None)
                    boot = self._boot_threads.pop(node_id, None)
                if agent is not None:
                    agent.stop()
                    if boot is not None:
                        boot.join(timeout=10.0)
                    agent.join(timeout=5.0)
                self.store.delete_entity(
                    names.TABLE_NODES, pool.id, node_id)

    def _teardown_slice(self, pool_id: str, slice_index: int) -> None:
        with self._lock:
            agents = self._agents.get(pool_id, {})
            victims = [a for a in agents.values()
                       if a.identity.slice_index == slice_index]
        for agent in victims:
            agent.stop()
        for agent in victims:
            node_id = agent.identity.node_id
            # Join the boot thread first: an agent still inside
            # start() has not registered its worker/heartbeat threads
            # yet, and a late state write from it would clobber the
            # replacement agent's row.
            with self._lock:
                boot = self._boot_threads.pop(node_id, None)
            if boot is not None:
                boot.join(timeout=10.0)
            agent.join(timeout=5.0)
            with self._lock:
                agents.pop(node_id, None)
            self.store.delete_entity(names.TABLE_NODES, pool_id,
                                     node_id)
        # Rows may exist without an in-process agent (fresh CLI
        # process attaching to an existing fake pool): the slice's
        # node entities must go regardless, like a real substrate's
        # teardown would take the machines' registrations with it.
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool_id)):
            if int(row.get("slice_index", -1)) == slice_index:
                try:
                    self.store.delete_entity(names.TABLE_NODES,
                                             pool_id, row["_rk"])
                except Exception:  # noqa: BLE001 - already gone
                    pass

    def recreate_slice(self, pool: PoolSettings, slice_index: int) -> None:
        self._teardown_slice(pool.id, slice_index)
        _, workers = self._pool_shape(pool)
        for w in range(workers):
            self._spawn_agent(pool, slice_index, w,
                              slice_index * workers + w)

    def deallocate_slice(self, pool: PoolSettings,
                         slice_index: int) -> None:
        self._teardown_slice(pool.id, slice_index)

    def suspend_pool(self, pool: PoolSettings) -> None:
        """Stop agents but keep node entities (marked suspended)."""
        with self._lock:
            agents = list(self._agents.get(pool.id, {}).values())
        for agent in agents:
            agent.stop()
        for agent in agents:
            node_id = agent.identity.node_id
            with self._lock:
                boot = self._boot_threads.pop(node_id, None)
            if boot is not None:
                boot.join(timeout=10.0)
            agent.join(timeout=5.0)
            with self._lock:
                self._agents.get(pool.id, {}).pop(node_id, None)
            try:
                self.store.merge_entity(names.TABLE_NODES, pool.id,
                                        node_id, {"state": "suspended"})
            except Exception:
                pass

    def start_pool(self, pool: PoolSettings) -> None:
        """Respawn agents for suspended node entities."""
        for row in list(self.store.query_entities(
                names.TABLE_NODES, partition_key=pool.id)):
            self._spawn_agent(pool, int(row.get("slice_index", 0)),
                              int(row.get("worker_index", 0)),
                              int(row.get("node_index", 0)))

    def ensure_attached(self, pool: PoolSettings) -> None:
        """Revive simulated agents for node entities that have no live
        in-process agent (fresh CLI process attaching to a fake pool)."""
        rows = list(self.store.query_entities(
            names.TABLE_NODES, partition_key=pool.id))
        with self._lock:
            live = set(self._agents.get(pool.id, {}))
        for row in rows:
            if row["_rk"] in live:
                continue
            self._spawn_agent(pool, int(row.get("slice_index", 0)),
                              int(row.get("worker_index", 0)),
                              int(row.get("node_index", 0)))

    def get_remote_login(self, pool_id: str,
                         node_id: str) -> Optional[tuple[str, int]]:
        try:
            row = self.store.get_entity(names.TABLE_NODES, pool_id, node_id)
        except KeyError:
            return None
        return row["internal_ip"], 22

    # ------------------------- test helpers ----------------------------

    def agent(self, pool_id: str, node_id: str) -> Optional[NodeAgent]:
        with self._lock:
            return self._agents.get(pool_id, {}).get(node_id)

    def crash_node(self, pool_id: str, node_id: str) -> Optional[dict]:
        """Hard-kill one node's agent (stop without cleanup — a real
        crash writes no 'offline' state). Returns the revival context
        for revive_node, or None when the node has no live agent."""
        with self._lock:
            agent = self._agents.get(pool_id, {}).get(node_id)
        if agent is None:
            return None
        context = {"identity": agent.identity, "pool": agent.pool,
                   "work_dir": agent.work_dir}
        agent.stop_event.set()
        agent.join(timeout=5.0)
        with self._lock:
            self._agents.get(pool_id, {}).pop(node_id, None)
            self._boot_threads.pop(node_id, None)
        return context

    def crash_agent_hard(self, pool_id: str,
                         node_id: str) -> Optional[dict]:
        """Simulate the agent PROCESS dying while its tasks live on
        (the crash-restart adoption shape): threads cannot be killed
        in-process, so the agent is marked abandoned — every
        in-flight completion path cuts off before its first
        post-exit store write, heartbeats stop (no offline write, no
        graceful lease release), and the task subprocesses — their
        own sessions, exactly like a real agent crash — keep
        running. Revive with revive_node on the SAME work_dir; the
        restarted agent re-adopts from the slot ledgers."""
        with self._lock:
            agent = self._agents.get(pool_id, {}).get(node_id)
        if agent is None:
            return None
        context = {"identity": agent.identity, "pool": agent.pool,
                   "work_dir": agent.work_dir}
        agent._abandoned = True
        agent.heartbeat_blackout_until = float("inf")
        agent.lease_blackout_until = float("inf")
        agent.stop_event.set()
        with self._lock:
            self._agents.get(pool_id, {}).pop(node_id, None)
            self._boot_threads.pop(node_id, None)
        return context

    def revive_node(self, pool_id: str, context: dict) -> None:
        """Reboot a crashed node with the same identity."""
        kwargs = {
            "heartbeat_interval": self.heartbeat_interval,
            "poll_interval": 0.05, "gang_timeout": 60.0,
            "job_state_ttl": 0.2, "node_stale_seconds": 3.0,
            "nodeprep": None, "substrate": self,
        }
        kwargs.update(self.agent_kwargs)
        revived = NodeAgent(
            self.store, context["identity"], context["pool"],
            work_dir=context["work_dir"], **kwargs)
        thread = threading.Thread(
            target=self._boot_agent, args=(revived,), daemon=True)
        with self._lock:
            self._agents.setdefault(pool_id, {})[
                context["identity"].node_id] = revived
            self._boot_threads[context["identity"].node_id] = thread
        thread.start()

    def start_chaos(self, pool_id: str, kill_interval: float = 1.0,
                    revive_after: float = 0.5,
                    seed: int = 0) -> threading.Event:
        """Fault injection: periodically hard-kill a random node's
        agent (stop without cleanup — simulating a crash) and revive
        it shortly after. Returns a stop event. Exercises orphan
        reclaim, message redelivery, and heartbeat staleness under
        continuous failure (the fault-injection capability SURVEY.md
        5.3 notes the reference lacks entirely). For a DETERMINISTIC
        schedule of these (plus wedges, mid-run kills, store faults,
        heartbeat blackouts) use chaos.ChaosPlan + chaos.drill."""
        import random
        stop = threading.Event()
        rng = random.Random(seed)

        def _chaos_loop():
            while not stop.wait(kill_interval):
                with self._lock:
                    agents = list(self._agents.get(pool_id, {}))
                if not agents:
                    continue
                context = self.crash_node(pool_id, rng.choice(agents))
                if context is None:
                    continue
                if stop.wait(revive_after):
                    return
                self.revive_node(pool_id, context)

        thread = threading.Thread(target=_chaos_loop, daemon=True,
                                  name=f"chaos-{pool_id}")
        thread.start()
        return stop

    def stop_all(self) -> None:
        with self._lock:
            pools = list(self._agents)
        for pool_id in pools:
            with self._lock:
                agents = list(self._agents.get(pool_id, {}).values())
            for agent in agents:
                agent.stop()
            for agent in agents:
                agent.join(timeout=5.0)

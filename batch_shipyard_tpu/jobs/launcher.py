"""Gang-task launcher: JAX distributed env synthesis over ICI/DCN.

This is the TPU-native replacement for the reference's MPI command-line
synthesis (_construct_mpi_command, convoy/batch.py:4362-4487): where the
reference chooses mpirun flags per runtime (IntelMPI/OpenMPI/MPICH/
MVAPICH) and per fabric (DAPL/OFA/OFI/UCX over Infiniband), we choose
environment variables per transport:

  - ICI (single pod slice): every worker runs the same SPMD program;
    ``jax.distributed.initialize`` gets coordinator = worker 0 of the
    slice, num_processes = workers in the slice, process_id = worker
    index. XLA collectives then ride the ICI torus with no further
    configuration.
  - DCN (multi-slice): additionally set MEGASCALE_* variables so libtpu
    spans slices over the data-center network; the per-slice mesh stays
    on ICI.
  - CPU/GPU pools (federation heterogeneity): plain jax.distributed
    over TCP.

The application command runs on EVERY instance (SPMD), unlike MPI where
mpirun on the primary spawns ranks: on TPU pods the same binary starts
on each worker and discovers its role from this env. The optional
coordination_command (reference: MultiInstanceSettings coordination
command, batch.py:4616) still runs on all instances before the
application command.

Also provides PyTorch/XLA (PJRT) env synthesis as the reference's
recipes supported PyTorch (recipes/PyTorch-GPU -> PyTorch/XLA on TPU).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from batch_shipyard_tpu.config.settings import (
    JaxDistributedSettings, MultiInstanceSettings, PoolSettings)


@dataclasses.dataclass(frozen=True)
class GangMember:
    """One task instance's placement, resolved at rendezvous time."""

    instance: int           # global process index [0, num_instances)
    node_id: str
    hostname: str
    internal_ip: str
    slice_index: int = 0
    worker_index: int = 0   # worker index within its slice


def _coordinator(members: list[GangMember]) -> GangMember:
    """Deterministic coordinator election: lowest (slice, worker,
    instance). Reference analog: MI 'primary' node; ours must be stable
    across restarts (SURVEY.md section 7 hard parts: no PMI)."""
    return min(members,
               key=lambda m: (m.slice_index, m.worker_index, m.instance))


def synthesize_jax_distributed_env(
        members: list[GangMember],
        member: GangMember,
        settings: JaxDistributedSettings,
        num_slices: int = 1,
        chips_per_worker: int = 4,
        accelerator_type: Optional[str] = None) -> dict[str, str]:
    """Build the distributed env for one gang member.

    Multi-slice (num_slices > 1) adds MEGASCALE_* DCN config; the
    transport setting can force ici/dcn, 'auto' infers from num_slices.
    """
    coord = _coordinator(members)
    num_processes = len(members)
    env: dict[str, str] = {
        # jax.distributed.initialize() reads these when args omitted.
        "JAX_COORDINATOR_ADDRESS":
            f"{coord.internal_ip}:{settings.coordinator_port}",
        "JAX_NUM_PROCESSES": str(num_processes),
        "JAX_PROCESS_ID": str(member.instance),
        # libtpu worker identity on a pod slice.
        "TPU_WORKER_ID": str(member.worker_index),
        "TPU_WORKER_HOSTNAMES": ",".join(
            m.internal_ip for m in sorted(
                members, key=lambda x: (x.slice_index, x.worker_index))
            if m.slice_index == member.slice_index),
        "TPU_CHIPS_PER_HOST_BOUNDS": f"2,2,1"
            if chips_per_worker == 4 else f"{chips_per_worker},1,1",
        # Distributed-service client resilience knobs.
        "JAX_DIST_HEARTBEAT_TIMEOUT_SECONDS":
            str(settings.heartbeat_timeout_seconds),
    }
    if accelerator_type:
        env["TPU_ACCELERATOR_TYPE"] = accelerator_type
    transport = settings.transport
    if transport == "auto":
        transport = "dcn" if num_slices > 1 else "ici"
    if transport == "dcn" and num_slices > 1:
        env.update({
            "MEGASCALE_COORDINATOR_ADDRESS": coord.internal_ip,
            "MEGASCALE_NUM_SLICES": str(num_slices),
            "MEGASCALE_SLICE_ID": str(member.slice_index),
            "MEGASCALE_PORT": str(settings.coordinator_port + 1),
        })
    return env


def synthesize_pytorch_xla_env(members: list[GangMember],
                               member: GangMember,
                               coordinator_port: int = 8476,
                               ) -> dict[str, str]:
    """PJRT env for PyTorch/XLA on TPU (recipes/PyTorch-GPU analog)."""
    coord = _coordinator(members)
    return {
        "PJRT_DEVICE": "TPU",
        "MASTER_ADDR": coord.internal_ip,
        "MASTER_PORT": str(coordinator_port),
        "WORLD_SIZE": str(len(members)),
        "RANK": str(member.instance),
    }


def synthesize_gang_env(members: list[GangMember],
                        member: GangMember,
                        mi: MultiInstanceSettings,
                        pool: PoolSettings) -> dict[str, str]:
    """Full env for one gang member per the task's multi_instance
    settings + pool topology."""
    env: dict[str, str] = {}
    num_slices = pool.tpu.num_slices if pool.tpu is not None else 1
    chips = pool.tpu.chips_per_worker if pool.tpu is not None else 0
    atype = pool.tpu.accelerator_type if pool.tpu is not None else None
    if mi.jax_distributed.enabled:
        env.update(synthesize_jax_distributed_env(
            members, member, mi.jax_distributed, num_slices=num_slices,
            chips_per_worker=chips or 4, accelerator_type=atype))
    if mi.pytorch_xla:
        env.update(synthesize_pytorch_xla_env(
            members, member, mi.jax_distributed.coordinator_port))
    return env

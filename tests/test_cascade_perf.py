"""Cascade lease-gated replication + perf pipeline tests (reference:
cascade/cascade.py lease gate :574-635, perf.py, graph.py)."""

import concurrent.futures
import threading
import time

from batch_shipyard_tpu.agent import perf
from batch_shipyard_tpu.agent.cascade import (
    CascadeImageProvisioner, global_resources_loaded,
    populate_global_resources)
from batch_shipyard_tpu.agent.node_agent import NodeIdentity
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.graph import perf_graph
from batch_shipyard_tpu.state.memory import MemoryStateStore


class FakeAgent:
    """Just enough agent surface for the provisioner."""

    def __init__(self, store, pool_id, node_id):
        self.store = store
        self.identity = NodeIdentity(
            pool_id=pool_id, node_id=node_id, node_index=0,
            hostname=node_id, internal_ip="10.0.0.1")
        self.stop_event = threading.Event()


def test_populate_and_loaded_flag():
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["img1:latest", "img2:v2"],
                              concurrent_downloads=2)
    agent = FakeAgent(store, "p", "n0")
    assert not global_resources_loaded(store, "p", "n0")
    prov = CascadeImageProvisioner(store, puller=lambda kind, img: 0)
    prov.distribute_global_resources(agent)
    assert global_resources_loaded(store, "p", "n0")


def test_concurrency_gate_bounds_parallel_pulls():
    """With K lock slots, at most K nodes pull the same image at
    once (the reference's hash.{0..N} blob-lease gate)."""
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["big:latest"],
                              concurrent_downloads=2)
    active = []
    max_active = []
    lock = threading.Lock()

    def slow_pull(kind, image):
        with lock:
            active.append(1)
            max_active.append(len(active))
        time.sleep(0.1)
        with lock:
            active.pop()
        return 0

    def node_run(idx):
        agent = FakeAgent(store, "p", f"n{idx}")
        prov = CascadeImageProvisioner(store, puller=slow_pull)
        prov.distribute_global_resources(agent)

    with concurrent.futures.ThreadPoolExecutor(6) as pool:
        list(pool.map(node_run, range(6)))
    assert max(max_active) <= 2
    # every node finished its pull
    for idx in range(6):
        assert global_resources_loaded(store, "p", f"n{idx}")


def test_failed_pull_not_recorded_loaded():
    store = MemoryStateStore()
    populate_global_resources(store, "p", ["bad:latest"])
    agent = FakeAgent(store, "p", "n0")
    prov = CascadeImageProvisioner(store, puller=lambda k, i: 1)
    prov.distribute_global_resources(agent)
    assert not global_resources_loaded(store, "p", "n0")


def test_kind_qualified_keys_shared_between_paths():
    """__call__ with kind must hit the same manifest rows as
    populate_global_resources."""
    store = MemoryStateStore()
    populate_global_resources(store, "p", [],
                              singularity_images=["simg:1"])
    pulls = []
    prov = CascadeImageProvisioner(
        store, puller=lambda kind, img: pulls.append((kind, img)) or 0)
    agent = FakeAgent(store, "p", "n0")
    prov(agent, ["simg:1"], kind="singularity")
    assert pulls == [("singularity", "simg:1")]


def test_perf_pipeline_and_gantt():
    store = MemoryStateStore()
    t0 = time.time()
    perf.emit(store, "p", "n0", "nodeprep", "start", timestamp=t0)
    perf.emit(store, "p", "n0", "cascade", "pull.start:img",
              timestamp=t0 + 0.5)
    perf.emit(store, "p", "n0", "cascade", "pull.end:img",
              timestamp=t0 + 2.0)
    perf.emit(store, "p", "n0", "cascade", "global_resources_loaded",
              timestamp=t0 + 2.1)
    perf.emit(store, "p", "n0", "nodeprep", "end", timestamp=t0 + 2.5)
    data = perf_graph.coalesce_data(store, "p")
    assert abs(data["nodes"]["n0"]["nodeprep"]["seconds"] - 2.5) < 1e-6
    assert abs(data["images"]["n0"]["img"] - 1.5) < 1e-6
    assert abs(data["nodes"]["n0"]["global_resources_loaded"][
        "seconds"] - 2.1) < 1e-6
    text = perf_graph.render_text_gantt(data)
    assert "nodeprep" in text and "#" in text


def test_perf_event_collision_bump():
    store = MemoryStateStore()
    ts = time.time()
    for _ in range(5):
        perf.emit(store, "p", "n0", "s", "same_event", timestamp=ts)
    assert len(perf.query(store, "p")) == 5


def test_registry_login_before_pulls(monkeypatch):
    """Private-registry auth (reference scripts/registry_login.sh):
    registry rows ride the pool manifest; nodes docker-login (secret://
    password resolved on node, passed via stdin never argv) and run
    gcloud auth configure-docker for Artifact Registry rows — all
    BEFORE the first pull."""
    monkeypatch.setenv("REG_PW_TEST", "hunter2-secret")
    store = MemoryStateStore()
    registries = [
        settings_mod.DockerRegistry(
            server="reg.example.com", username="svc",
            password="secret://env/REG_PW_TEST"),
        settings_mod.DockerRegistry(
            server="us-docker.pkg.dev", auth="gcloud"),
    ]
    populate_global_resources(
        store, "p", ["reg.example.com/private/img:1"],
        registries=registries)
    # The stored manifest holds the REF, not the plaintext.
    rows = list(store.query_entities("images", partition_key="p"))
    reg_rows = [r for r in rows if r.get("kind") == "registry"]
    assert len(reg_rows) == 2
    assert all("hunter2" not in str(r) for r in reg_rows)

    calls = []

    def login_runner(argv, stdin_data):
        calls.append((list(argv), stdin_data))
        return 0

    pulls = []
    prov = CascadeImageProvisioner(
        store, puller=lambda kind, img: pulls.append(img) or 0,
        login_runner=login_runner)
    agent = FakeAgent(store, "p", "n0")
    prov.distribute_global_resources(agent)
    # Logins happened, before any pull.
    assert pulls == ["reg.example.com/private/img:1"]
    assert len(calls) == 2
    by_server = {c[0][2] if c[0][0] == "docker" else c[0][3]: c
                 for c in calls}
    docker_call = by_server["reg.example.com"]
    assert docker_call[0][:3] == ["docker", "login", "reg.example.com"]
    assert "--password-stdin" in docker_call[0]
    assert docker_call[1] == "hunter2-secret"       # resolved, stdin
    assert "hunter2-secret" not in " ".join(docker_call[0])  # not argv
    gcloud_call = by_server["us-docker.pkg.dev"]
    assert gcloud_call[0][:3] == ["gcloud", "auth", "configure-docker"]
    # Idempotent: a second distribute does not re-login.
    prov.distribute_global_resources(agent)
    assert len(calls) == 2
    # Registry rows never count as pending image resources.
    assert global_resources_loaded(store, "p", "n0")


def test_registry_login_failure_raises():
    store = MemoryStateStore()
    populate_global_resources(
        store, "p", ["img:1"],
        registries=[settings_mod.DockerRegistry(
            server="bad.example.com", username="u", password="pw")])
    prov = CascadeImageProvisioner(
        store, puller=lambda kind, img: 0,
        login_runner=lambda argv, stdin: 1)
    agent = FakeAgent(store, "p", "n0")
    try:
        prov.distribute_global_resources(agent)
    except RuntimeError as exc:
        assert "bad.example.com" in str(exc)
    else:
        raise AssertionError("expected login failure to raise")


def test_direct_download_preloaded_tarball(tmp_path):
    """Cascade direct-download mode (reference cascade.py:574
    _direct_download_resources_async): a preloaded tarball streams
    from the object store to the node cache — byte-identical — and
    re-populating the manifest does not sever the source_blob
    binding."""
    import os

    from batch_shipyard_tpu.agent.cascade import (
        CascadeImageProvisioner, preload_image_tarball)

    store = MemoryStateStore()
    payload = os.urandom(1024 * 256)
    chunks = [payload[i:i + 65536]
              for i in range(0, len(payload), 65536)]
    blob_key = preload_image_tarball(store, "p", "preload/img:1",
                                     iter(chunks))
    # populate AFTER preload (the pool-add ordering) keeps the blob.
    populate_global_resources(store, "p", ["preload/img:1"])
    rows = list(store.query_entities("images", partition_key="p"))
    assert rows[0]["source_blob"] == blob_key

    prov = CascadeImageProvisioner(store)
    prov._cache_dir = str(tmp_path)
    agent = FakeAgent(store, "p", "n0")
    prov.distribute_global_resources(agent)
    assert global_resources_loaded(store, "p", "n0")
    cached = tmp_path / os.path.basename(blob_key)
    assert cached.read_bytes() == payload

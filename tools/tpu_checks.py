"""On-chip numeric checks that cannot run in the CPU-forced CI suite.

Run from the repo root in the TPU bench environment:

    python tools/tpu_checks.py

Covers the flash-ring path (VERDICT r1 weak #3 / next #10): the
3-case rotation switch + logsumexp merge of
ops/ring_attention.ring_attention_virtual_shards — the same code the
shard_map ring body executes per rotation — against the dense oracle,
forward AND backward, at unit input scale, on the real chip.

Pallas interpret mode aborts inside shard_map on CPU, so CI covers the
building blocks in interpret mode only; this harness is the real-MXU
validation. Matmul precision is forced to 'highest' so fp32 comparisons
are meaningful (the TPU default is bf16-pass matmuls, ~1e-3 relative).
"""

from __future__ import annotations

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import jax

jax.config.update("jax_default_matmul_precision", "highest")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def check_flash_ring_virtual_shards() -> None:
    from batch_shipyard_tpu.ops import attention as attn
    from batch_shipyard_tpu.ops import ring_attention as ring

    rng = np.random.RandomState(3)
    shape = (1, 512, 2, 64)  # unit scale: no atol masking
    q = jnp.asarray(rng.randn(*shape), jnp.float32)
    k = jnp.asarray(rng.randn(*shape), jnp.float32)
    v = jnp.asarray(rng.randn(*shape), jnp.float32)

    for causal in (True, False):
        for sp in (2, 4):
            def loss_ring(q, k, v):
                return jnp.sum(ring.ring_attention_virtual_shards(
                    q, k, v, sp=sp, causal=causal) ** 2)

            def loss_ref(q, k, v):
                return jnp.sum(attn.mha_reference(
                    q, k, v, causal=causal) ** 2)

            out_ring = jax.jit(
                lambda q, k, v: ring.ring_attention_virtual_shards(
                    q, k, v, sp=sp, causal=causal))(q, k, v)
            out_ref = attn.mha_reference(q, k, v, causal=causal)
            rel_f = (np.linalg.norm(np.asarray(out_ring - out_ref)) /
                     np.linalg.norm(np.asarray(out_ref)))
            g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
                q, k, v)
            g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(
                q, k, v)
            rels = []
            for a, b in zip(g_ring, g_ref):
                a, b = np.asarray(a), np.asarray(b)
                rels.append(np.linalg.norm(a - b) /
                            max(np.linalg.norm(b), 1e-30))
            ok = rel_f < 1e-4 and all(r < 5e-4 for r in rels)
            print(f"flash-ring sp={sp} causal={causal}: "
                  f"fwd_rel={rel_f:.2e} "
                  f"grad_rels={[f'{r:.2e}' for r in rels]} "
                  f"{'OK' if ok else 'FAIL'}")
            if not ok:
                raise SystemExit(1)


def main() -> None:
    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    check_flash_ring_virtual_shards()
    print("ALL TPU CHECKS OK")


if __name__ == "__main__":
    main()

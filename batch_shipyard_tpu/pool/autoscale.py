"""Scenario-based pool autoscale.

Reference analog: convoy/autoscale.py — generates Azure Batch autoscale
*formula text* from scenario names (_AUTOSCALE_SCENARIOS :351:
active_tasks, pending_tasks, workday, workday_with_offpeak_max_low_
priority, weekday, weekend) with knobs for min/max/max-increment,
bias_last_sample, rebalance_preemption_percentage (:92-300).

TPU-native re-design: there is no hosted formula evaluator, so this
module IS the evaluator — `evaluate` samples live task/node state from
the state store and produces a target slice count; `autoscale_tick`
applies it through the substrate. The same scenario names and knobs are
honored. A user `formula` is a restricted Python expression evaluated
over the sampled variables (the power-user escape hatch the reference
gives via raw formulas).

TPU quantization: targets are rounded to whole pod slices (a v5e-16
cannot grow by one VM), the slice-atomicity constraint from SURVEY.md
section 7.
"""

from __future__ import annotations

import dataclasses
import datetime
import math
from typing import Optional

from batch_shipyard_tpu.config.settings import (
    AutoscaleScenarioSettings, PoolSettings)
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Samples:
    """The $ActiveTasks/$PendingTasks/$CurrentDedicated analog."""

    active_tasks: int       # running + assigned
    pending_tasks: int      # pending (incl. waiting deps)
    current_nodes: int
    # Nodes the provider reclaimed (spot/low-priority preemption) —
    # the $PreemptedNodeCount sample of the reference's formulas
    # (autoscale.py:92-104).
    preempted_nodes: int
    task_slots_per_node: int
    now: datetime.datetime


def sample(store: StateStore, pool: PoolSettings,
           now: Optional[datetime.datetime] = None) -> Samples:
    active = 0
    pending = 0
    for job in store.query_entities(names.TABLE_JOBS,
                                    partition_key=pool.id):
        if job.get("state") != "active":
            continue
        pk = names.task_pk(pool.id, job["_rk"])
        for task in store.query_entities(names.TABLE_TASKS,
                                         partition_key=pk):
            state = task.get("state")
            if state in ("running", "assigned"):
                active += 1
            elif state in names.CLAIMABLE_TASK_STATES:
                # pending + preempted-awaiting-reclaim: both are
                # demand the pool has not yet placed.
                pending += 1
    all_nodes = pool_mgr.list_nodes(store, pool.id)
    nodes = [n for n in all_nodes if n.state in pool_mgr.READY_STATES]
    preempted = [n for n in all_nodes if n.state == "preempted"]
    return Samples(
        active_tasks=active, pending_tasks=pending,
        current_nodes=len(nodes),
        preempted_nodes=len(preempted),
        task_slots_per_node=pool.task_slots_per_node,
        now=now or util.utcnow())


def _clamp(value: int, scenario: AutoscaleScenarioSettings,
           current: int) -> int:
    lo = scenario.minimum_vm_count_dedicated
    hi = scenario.maximum_vm_count_dedicated
    value = max(lo, min(hi, value))
    inc = scenario.maximum_vm_increment_dedicated
    if inc > 0 and value > current:
        value = min(value, current + inc)
    return value


def _in_time_range(now: datetime.datetime, scenario_name: str,
                   time_ranges: dict) -> bool:
    """Work-hours check for the workday/weekday scenarios. Defaults
    mirror the reference: Mon-Fri, 08:00-18:00 (autoscale.py:211+)."""
    work_days = time_ranges.get("weekdays", {"start": 0, "end": 4})
    work_hours = time_ranges.get("work_hours", {"start": 8, "end": 17})
    is_work_day = work_days["start"] <= now.weekday() <= work_days["end"]
    is_work_hour = (work_hours["start"] <= now.hour
                    <= work_hours["end"])
    if scenario_name == "weekend":
        return not is_work_day
    if scenario_name == "weekday":
        return is_work_day
    return is_work_day and is_work_hour


def evaluate(store: StateStore, pool: PoolSettings,
             now: Optional[datetime.datetime] = None) -> dict:
    """Compute the autoscale decision for a pool. Returns
    {target_nodes, target_slices, reason} without applying it."""
    autoscale = pool.autoscale
    samples = sample(store, pool, now)
    rebalance_applied = False
    if autoscale.formula:
        target = _eval_formula(autoscale.formula, samples)
        reason = "user formula"
    else:
        scenario = autoscale.scenario
        if scenario is None:
            return {"target_nodes": samples.current_nodes,
                    "target_slices": None,
                    "reason": "no scenario configured"}
        name = scenario.name
        if name in ("active_tasks", "pending_tasks"):
            backlog = (samples.active_tasks if name == "active_tasks"
                       else samples.active_tasks + samples.pending_tasks)
            needed = math.ceil(backlog / max(
                1, samples.task_slots_per_node))
            if scenario.bias_last_sample:
                # Weight current demand 2:1 over capacity inertia.
                needed = math.ceil(
                    (2 * needed + samples.current_nodes) / 3)
            target = _clamp(needed, scenario, samples.current_nodes)
            reason = (f"{name}: backlog={backlog} "
                      f"slots/node={samples.task_slots_per_node}")
        elif name == "goodput":
            # Goodput-as-controller: size the fleet where the marginal
            # node stops paying for its own provisioning badput with
            # saved queueing badput (sched/policy.py autoscale_target —
            # the SAME function the fleet simulator prices, so the sim's
            # measured goodput deltas transfer to this live path).
            from batch_shipyard_tpu.sched import policy as sched_policy
            knobs = sched_policy.knobs_from_settings(
                getattr(pool, "sched_policy", None))
            raw, why = sched_policy.autoscale_target(
                pending_tasks=samples.pending_tasks,
                active_tasks=samples.active_tasks,
                current_nodes=samples.current_nodes,
                slots_per_node=samples.task_slots_per_node,
                knobs=knobs)
            target = _clamp(raw, scenario, samples.current_nodes)
            reason = f"goodput: {why}"
        elif name in ("workday", "weekday", "weekend",
                      "workday_with_offpeak_max_low_priority"):
            in_range = _in_time_range(samples.now, name,
                                      scenario.time_ranges)
            if in_range:
                dedicated = scenario.maximum_vm_count_dedicated
                low_priority = scenario.minimum_vm_count_low_priority
            elif name == "workday_with_offpeak_max_low_priority":
                # Off-peak: dedicated drops to minimum while cheap
                # low-priority capacity rises to its maximum
                # (reference offpeak semantics, autoscale.py:211+).
                dedicated = scenario.minimum_vm_count_dedicated
                low_priority = scenario.maximum_vm_count_low_priority
            else:
                dedicated = scenario.minimum_vm_count_dedicated
                low_priority = scenario.minimum_vm_count_low_priority
            if _rebalance_triggered(scenario, samples):
                # Preemption pressure: the provider is reclaiming
                # low-priority capacity faster than the threshold —
                # shift the low-priority share of the target into
                # dedicated (reference rebalance formula,
                # autoscale.py:92-135).
                dedicated = min(dedicated + low_priority,
                                scenario.maximum_vm_count_dedicated)
                low_priority = 0
                rebalance_applied = True
            target = _clamp(dedicated, scenario,
                            samples.current_nodes) + low_priority
            reason = (f"{name}: in_range={in_range} at {samples.now}"
                      + (" [rebalanced to dedicated on preemption]"
                         if rebalance_applied else ""))
        else:
            raise ValueError(f"unknown autoscale scenario {name!r}")
    target_slices = None
    if pool.tpu is not None:
        per_slice = pool.tpu.workers_per_slice
        target_slices = max(
            0 if target == 0 else 1,
            math.ceil(target / per_slice))
        target = target_slices * per_slice
    return {"target_nodes": target, "target_slices": target_slices,
            "current_nodes": samples.current_nodes,
            "active_tasks": samples.active_tasks,
            "pending_tasks": samples.pending_tasks,
            "preempted_nodes": samples.preempted_nodes,
            # True only when the dedicated/low-priority shift was
            # actually applied (the workday-family branch) — backlog
            # scenarios and user formulas have no class mix to shift.
            "rebalance": rebalance_applied,
            "reason": reason}


def _rebalance_triggered(scenario: AutoscaleScenarioSettings,
                         samples: Samples) -> bool:
    """Preemption-pressure signal: percentage of current capacity the
    provider has reclaimed >= rebalance_preemption_percentage
    (reference autoscale.py:121-131 'preemptedpercent >= threshold';
    the knob is 0-100)."""
    rpp = scenario.rebalance_preemption_percentage
    if rpp is None:
        return False
    total = samples.current_nodes + samples.preempted_nodes
    if total == 0:
        return False
    return 100.0 * samples.preempted_nodes / total >= float(rpp)


_FORMULA_BUILTINS = {"min": min, "max": max, "ceil": math.ceil,
                     "floor": math.floor, "abs": abs, "round": round}

_ALLOWED_AST_NODES = (
    "Expression", "BinOp", "UnaryOp", "BoolOp", "Compare", "IfExp",
    "Call", "Name", "Load", "Constant", "Add", "Sub", "Mult", "Div",
    "FloorDiv", "Mod", "Pow", "USub", "UAdd", "And", "Or", "Not",
    "Eq", "NotEq", "Lt", "LtE", "Gt", "GtE", "Tuple", "List",
)


def _validate_formula_ast(formula: str, allowed_names: set[str]) -> None:
    """AST allowlist: arithmetic/comparison expressions over known
    names only. No attribute access, subscripts, lambdas, or
    comprehensions — which closes the empty-__builtins__ escape chains
    (().__class__... style)."""
    import ast
    try:
        tree = ast.parse(formula, mode="eval")
    except SyntaxError as exc:
        raise ValueError(f"autoscale formula syntax error: {exc}")
    for node in ast.walk(tree):
        kind = type(node).__name__
        if kind not in _ALLOWED_AST_NODES:
            raise ValueError(
                f"autoscale formula: disallowed construct {kind}")
        if isinstance(node, ast.Name) and node.id not in allowed_names:
            raise ValueError(
                f"autoscale formula: unknown name {node.id!r}")
        if isinstance(node, ast.Call) and not isinstance(
                node.func, ast.Name):
            raise ValueError(
                "autoscale formula: only direct function calls to "
                "the math subset are allowed")


def _eval_formula(formula: str, samples: Samples) -> int:
    """Evaluate a user formula over sampled variables; AST-validated
    against an allowlist before eval."""
    variables = {
        "active_tasks": samples.active_tasks,
        "pending_tasks": samples.pending_tasks,
        "current_nodes": samples.current_nodes,
        "task_slots_per_node": samples.task_slots_per_node,
        "hour": samples.now.hour,
        "weekday": samples.now.weekday(),
    }
    _validate_formula_ast(
        formula, set(_FORMULA_BUILTINS) | set(variables))
    try:
        result = eval(  # noqa: S307 - AST-allowlisted above
            formula, {"__builtins__": {}},
            {**_FORMULA_BUILTINS, **variables})
    except Exception as exc:
        raise ValueError(f"autoscale formula error: {exc}") from exc
    if not isinstance(result, (int, float)):
        raise ValueError("autoscale formula must yield a number")
    return int(result)


def enable_autoscale(store: StateStore, pool: PoolSettings) -> None:
    store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                       {"autoscale_enabled": True})


def disable_autoscale(store: StateStore, pool: PoolSettings) -> None:
    store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                       {"autoscale_enabled": False})


def autoscale_tick(store: StateStore, substrate, pool: PoolSettings,
                   now: Optional[datetime.datetime] = None) -> dict:
    """One evaluation + application cycle (the hosted evaluator loop the
    reference delegates to Azure Batch, batch.py:1636-1755)."""
    entity = pool_mgr.get_pool(store, pool.id)
    if not entity.get("autoscale_enabled"):
        decision = evaluate(store, pool, now)
        decision["applied"] = False
        return decision
    # Substrates that can detect provider reclamation refresh node
    # states first, so the preemption sample feeding
    # rebalance_preemption_percentage is live (tpu_vm polls slice
    # states; fake/localhost have nothing to poll).
    refresh = getattr(substrate, "refresh_node_states", None)
    if refresh is not None:
        try:
            refresh(pool)
        except Exception:
            logger.exception("node-state refresh failed for %s",
                             pool.id)
    decision = evaluate(store, pool, now)
    _record_preemptions(store, entity, pool.id,
                        decision["preempted_nodes"])
    if decision["target_slices"] is not None:
        current_slices = len({
            n.slice_index for n in pool_mgr.list_nodes(store, pool.id)})
        if decision["target_slices"] != current_slices:
            logger.info("autoscale: %s slices %d -> %d (%s)", pool.id,
                        current_slices, decision["target_slices"],
                        decision["reason"])
            with goodput_events.span(
                    store, pool.id, goodput_events.NODE_PROVISIONING,
                    attrs={"reason": "autoscale_resize",
                           "from_slices": current_slices,
                           "to_slices": decision["target_slices"]}):
                substrate.resize_pool(pool, decision["target_slices"])
            decision["applied"] = True
            return decision
    else:
        # Non-TPU pools: resize takes a node count.
        current = len(pool_mgr.list_nodes(store, pool.id))
        if decision["target_nodes"] != current:
            logger.info("autoscale: %s nodes %d -> %d (%s)", pool.id,
                        current, decision["target_nodes"],
                        decision["reason"])
            with goodput_events.span(
                    store, pool.id, goodput_events.NODE_PROVISIONING,
                    attrs={"reason": "autoscale_resize",
                           "from_nodes": current,
                           "to_nodes": decision["target_nodes"]}):
                substrate.resize_pool(pool, decision["target_nodes"])
            decision["applied"] = True
            return decision
    decision["applied"] = False
    return decision


def _record_preemptions(store: StateStore, pool_entity: dict,
                        pool_id: str, preempted_nodes: int) -> None:
    """Goodput: record provider reclamation as it is OBSERVED. A
    rising count emits an instantaneous marker (the preemption
    counter); when the count drains back to zero the whole outage is
    emitted as ONE preempted->recovered SPAN (tick-granular downtime,
    priced as provisioning badput). State rides the pool entity so
    dedupe and the open-outage start survive daemon restarts."""
    import time as time_mod
    last = int(pool_entity.get("goodput_preempted_nodes", 0) or 0)
    since = pool_entity.get("goodput_preempted_since")
    now = time_mod.time()
    patch: dict = {}
    if preempted_nodes != last:
        patch["goodput_preempted_nodes"] = preempted_nodes
    if preempted_nodes > last:
        if since is None:
            patch["goodput_preempted_since"] = now
        goodput_events.emit(
            store, pool_id, goodput_events.NODE_PREEMPTED,
            start=now, end=now,
            attrs={"preempted_nodes": preempted_nodes,
                   "newly_preempted": preempted_nodes - last})
    elif preempted_nodes == 0 and last > 0 and since is not None:
        goodput_events.emit(
            store, pool_id, goodput_events.NODE_PREEMPTED,
            start=float(since), end=now,
            attrs={"recovered": True, "nodes": last})
        patch["goodput_preempted_since"] = None
    if patch:
        try:
            store.merge_entity(names.TABLE_POOLS, "pools", pool_id,
                               patch)
        except Exception:  # noqa: BLE001 - accounting is advisory
            logger.exception("preemption bookkeeping failed for %s",
                             pool_id)


def run_daemon(store: StateStore, substrate, pool: PoolSettings,
               stop_event=None, interval: Optional[float] = None) -> None:
    """Periodic evaluation loop honoring
    autoscale.evaluation_interval_seconds (the hosted evaluator's
    cadence)."""
    import threading
    import time as time_mod
    stop = stop_event or threading.Event()
    period = interval or pool.autoscale.evaluation_interval_seconds
    while not stop.wait(period):
        try:
            autoscale_tick(store, substrate, pool)
        except Exception:
            logger.exception("autoscale tick failed for %s", pool.id)

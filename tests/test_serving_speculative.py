"""Speculative decoding INSIDE the continuous batching engine
(models/serving.py SpeculativeConfig): per-slot ragged draft/verify —
slots advance 1..gamma+1 tokens per step — must stay greedy-exact
against the non-speculative engine across mixed accept/reject slots,
mid-draft stops, mid-flight admission, dense AND paged KV, plus the
stats/plumbing and the serving_speculative bench phase."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import serving
from batch_shipyard_tpu.models import transformer as tfm

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = tfm.TransformerConfig(
    vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
    d_ff=64, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)
DCFG = tfm.TransformerConfig(
    vocab_size=97, d_model=16, n_layers=1, n_heads=2, d_head=8,
    d_ff=32, max_seq_len=64, dtype=jnp.float32,
    param_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return tfm.TransformerLM(CFG).init(
        jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(scope="module")
def dparams():
    return tfm.TransformerLM(DCFG).init(
        jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(scope="module")
def noisy_params(params):
    """A lightly-perturbed copy of the target as draft: agrees often
    but not always — every round mixes accepted and rejected drafts
    across slots (the ragged per-slot commit path)."""
    rng = np.random.RandomState(11)
    return jax.tree_util.tree_map(
        lambda p: p + jnp.asarray(0.02 * rng.randn(*p.shape),
                                  p.dtype), params)


_REF_RUNS: dict = {}


def reference_greedy(params, prompt, num_tokens, max_decode_len=64):
    """Lockstep greedy reference. The decoder fn is memoized per
    max_decode_len (and jax caches compiles per (prompt_len,
    num_tokens)) — tests below standardize prompt lengths and token
    counts so the suite pays a handful of reference compiles, not one
    per call."""
    run = _REF_RUNS.get((id(params), max_decode_len))
    if run is None:
        run, _model = inf.make_decoder(CFG, params,
                                       max_decode_len=max_decode_len)
        _REF_RUNS[(id(params), max_decode_len)] = run
    tokens, _cache = run(jnp.asarray([prompt], jnp.int32), num_tokens,
                         jax.random.PRNGKey(0))
    return list(np.asarray(tokens[0, len(prompt):]))


def _drain(engine, max_steps=400):
    results = {}
    for _ in range(max_steps):
        for rid, toks in engine.step():
            results[rid] = toks
        if not engine.pending():
            break
    assert not engine.pending(), "engine failed to drain"
    return results


def _spec_engine(params, draft_cfg, draft_params, gamma=4,
                 num_slots=2, kv_page_size=None, **kw):
    return serving.ContinuousBatcher(
        CFG, params, num_slots=num_slots, max_decode_len=64,
        kv_page_size=kv_page_size,
        speculative=serving.SpeculativeConfig(
            draft_cfg, draft_params, gamma=gamma), **kw)


def test_mixed_acceptance_matches_nonspeculative(params,
                                                 noisy_params):
    """The core equivalence: 5 requests through a 2-slot speculative
    engine (perturbed draft -> per-slot mixed accept/reject every
    round), one of them submitted MID-FLIGHT while another slot is
    mid-generation, produce EXACTLY the tokens the non-speculative
    engine produces. (The paged-KV analog runs in
    test_paged_spec_crosses_pages_at_max_decode_len.)"""
    rng = np.random.RandomState(0)
    requests = [
        serving.Request(f"r{i}", list(rng.randint(0, 97, (4,))),
                        max_new_tokens=8)
        for i in range(4)
    ]
    late = serving.Request("late", list(rng.randint(0, 97, (4,))),
                           max_new_tokens=12)
    engine = _spec_engine(params, CFG, noisy_params, gamma=4)
    for req in requests:
        engine.submit(serving.Request(req.request_id, req.prompt,
                                      req.max_new_tokens))
    for _ in range(2):
        engine.step()  # slots are mid-generation now
    # Mid-flight admission: the free slot's target AND draft caches
    # prefill while the other slot keeps speculating.
    engine.submit(serving.Request(late.request_id, late.prompt,
                                  late.max_new_tokens))
    results = _drain(engine)
    assert set(results) == (
        {r.request_id for r in requests} | {"late"})
    for req in requests + [late]:
        want = reference_greedy(params, req.prompt,
                                req.max_new_tokens)
        assert results[req.request_id] == want, (
            req.request_id, results[req.request_id], want)
    stats = engine.spec_stats()
    # The perturbed draft must have produced BOTH accepts and rejects
    # (otherwise this test isn't exercising the ragged path).
    assert 0 < stats["accepted"] < stats["proposed"], stats


def test_hostile_draft_still_exact(params, dparams):
    """An unrelated random draft: near-zero acceptance, every round
    falls back to the target's correction token — output identical."""
    rng = np.random.RandomState(1)
    prompt = list(rng.randint(0, 97, (4,)))
    engine = _spec_engine(params, DCFG, dparams, gamma=3)
    engine.submit(serving.Request("h", prompt, max_new_tokens=8))
    results = _drain(engine)
    assert results["h"] == reference_greedy(params, prompt, 8)


def test_identical_draft_full_acceptance_and_midblock_stop(params):
    """Draft == target on ONE engine (slot reuse across sequential
    requests): (a) full acceptance — gamma+1 tokens commit per round,
    the bonus-token path; (b) an eos landing MID-BLOCK truncates the
    commit exactly like the non-speculative engine; (c) a
    max_new_tokens that is not a multiple of gamma+1 truncates the
    same way."""
    prompt = [5, 17, 31, 2]
    engine = _spec_engine(params, CFG, params, gamma=4, num_slots=1)
    engine.submit(serving.Request("f", prompt, max_new_tokens=12))
    results = _drain(engine)
    assert results["f"] == reference_greedy(params, prompt, 12)
    stats = engine.spec_stats()
    assert stats["accepted"] == stats["proposed"] > 0
    assert stats["acceptance_rate"] == 1.0
    # (b) eos at commit index 2: the first round commits 5 tokens, so
    # the stop happens mid-block and later committed tokens discard.
    prompt2 = [9, 9, 1, 42]
    full = reference_greedy(params, prompt2, 12)
    eos = full[2]
    want = full[:full.index(eos) + 1]
    engine.submit(serving.Request("e", prompt2, max_new_tokens=12,
                                  eos_id=eos))
    results = _drain(engine)
    assert results["e"] == want, (results["e"], want)
    # (c) truncation by max_new_tokens mid-block.
    engine.submit(serving.Request("t", prompt2, max_new_tokens=8))
    results = _drain(engine)
    assert results["t"] == reference_greedy(params, prompt2, 8)


def test_paged_spec_crosses_pages_at_max_decode_len(params,
                                                    noisy_params):
    """Paged + speculative at the boundary: prompt+max_new ==
    max_decode_len and verify blocks crossing page boundaries — the
    spec_window table margin routes tail writes to scratch; outputs
    stay exact and every page returns to the pool."""
    rng = np.random.RandomState(4)
    p1 = list(rng.randint(0, 97, (8,)))
    p2 = list(rng.randint(0, 97, (5,)))
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        speculative=serving.SpeculativeConfig(CFG, noisy_params,
                                              gamma=4))
    engine.submit(serving.Request("b1", p1, max_new_tokens=24))
    engine.submit(serving.Request("b2", p2, max_new_tokens=20))
    results = _drain(engine)
    assert results["b1"] == reference_greedy(params, p1, 24,
                                             max_decode_len=32)
    assert results["b2"] == reference_greedy(params, p2, 20,
                                             max_decode_len=32)
    pool = list(engine._free_pages) + list(engine._lru)
    assert len(pool) == len(set(pool))
    # All pages reclaimable after drain: free or parked unreferenced
    # in the prefix-cache LRU.
    assert len(pool) == 8
    assert all(ref == 0 for ref in engine._page_ref.values())


def test_overcommit_preemption_with_speculation(params, noisy_params):
    """Overcommit + speculation: pool pressure preempts victims
    mid-speculative-decode; resumption re-prefills BOTH caches and
    the greedy continuation is unchanged."""
    rng = np.random.RandomState(5)
    reqs = [serving.Request(f"p{i}", list(rng.randint(0, 97, (6,))),
                            max_new_tokens=18) for i in range(4)]
    engine = serving.ContinuousBatcher(
        CFG, params, num_slots=2, max_decode_len=32, kv_page_size=8,
        kv_num_pages=5, overcommit=True,
        speculative=serving.SpeculativeConfig(CFG, noisy_params,
                                              gamma=2))
    for r in reqs:
        engine.submit(r)
    results = _drain(engine, max_steps=800)
    assert set(results) == {r.request_id for r in reqs}
    assert engine.preemptions > 0, \
        "scenario failed to exercise preemption"
    for r in reqs:
        assert results[r.request_id] == reference_greedy(
            params, r.prompt, r.max_new_tokens,
            max_decode_len=32), r.request_id
    assert len(engine._free_pages) + len(engine._lru) == 5
    assert all(ref == 0 for ref in engine._page_ref.values())


def test_speculative_rejects_bad_configs(params, dparams):
    with pytest.raises(ValueError, match="temperature"):
        _spec_engine(params, DCFG, dparams,
                     sampling=inf.SamplingConfig(temperature=0.7))
    with pytest.raises(ValueError, match="gamma"):
        _spec_engine(params, DCFG, dparams, gamma=0)
    import dataclasses
    paged_draft = dataclasses.replace(DCFG, kv_page_size=8)
    with pytest.raises(ValueError, match="kv_page_size"):
        _spec_engine(params, paged_draft, dparams)
    other_vocab = dataclasses.replace(DCFG, vocab_size=96)
    with pytest.raises(ValueError, match="vocab_size"):
        _spec_engine(params, other_vocab, dparams)


def test_frontend_exposes_acceptance_rate(params, noisy_params):
    """server.py plumbing: /v1/stats and /metrics carry the engine's
    speculative counters."""
    import urllib.request

    from batch_shipyard_tpu.models.server import ServingFrontEnd
    engine = _spec_engine(params, CFG, noisy_params, gamma=3)
    front = ServingFrontEnd(engine, port=0).start()
    try:
        front.generate({"prompt": [4, 8, 15], "max_new_tokens": 9})
        with urllib.request.urlopen(f"{front.url}/v1/stats",
                                    timeout=30) as resp:
            stats = json.loads(resp.read())
        spec = stats["speculative"]
        assert spec["gamma"] == 3
        assert spec["proposed"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        with urllib.request.urlopen(f"{front.url}/metrics",
                                    timeout=30) as resp:
            text = resp.read().decode()
        assert "shipyard_serving_spec_acceptance_rate" in text
        assert "shipyard_serving_spec_proposed_tokens_total" in text
    finally:
        front.shutdown()


@pytest.mark.slow
def test_bench_serving_speculative_emits_metrics():
    """The serving_speculative bench phase (bench.py) reports
    tokens/s, TTFT/TPOT percentiles, and the measured acceptance
    rate, for dense and paged KV."""
    sys.path.insert(0, REPO_ROOT)
    import bench
    for page in (None, 8):
        rep = bench.bench_serving_speculative(
            num_requests=3, rate_hz=50.0, num_slots=2,
            max_decode_len=64, d_model=32, n_layers=1, n_heads=2,
            d_ff=64, draft_d_model=16, draft_n_layers=1, gamma=3,
            vocab_size=97, kv_page_size=page)
        assert rep["failed"] == 0
        assert rep["tokens_per_second"] > 0
        for key in ("ttft_ms", "tpot_ms"):
            assert set(rep[key]) == {"p50", "p90", "p99"}
        spec = rep["speculative"]
        assert spec["proposed"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        assert rep["kv_page_size"] == page


def test_silicon_proof_dry_run_has_serving_speculative_phase(
        tmp_path):
    """The silicon-proof skeleton (CI path) records the
    serving_speculative phase with the exact metric names it will
    emit on the chip (dense + paged)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools/silicon_proof.py"),
         "--dry-run", "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(
        (tmp_path / "SILICON_PROOF.json").read_text())
    phases = {p["phase"]: p for p in report["phases"]}
    spec = phases["serving_speculative"]
    assert spec["status"] == "dry_run"
    assert "bench.py" in spec["command"]
    assert "serving_speculative" in spec["command"]
    for variant in ("dense", "paged"):
        assert set(spec["metrics"][variant]) == {
            "tokens_per_second", "ttft_ms_p50", "tpot_ms_p50",
            "acceptance_rate"}


def test_paged_multitoken_insert_requires_spec_window(params):
    """Fail-fast guard (review finding): a multi-token insert into a
    paged cache WITHOUT a spec_window margin would clamp its tail
    table gather onto the slot's last live page — silent corruption.
    Only the serving engine (which sizes spec_window=gamma) may drive
    seq>1 paged inserts; everyone else must fail loudly."""
    import dataclasses
    cfg = dataclasses.replace(
        inf.decode_config(CFG, 32), kv_page_size=8, kv_num_pages=9)
    model = tfm.TransformerLM(cfg)
    cache = inf.init_cache(model, params, 1)
    with pytest.raises(ValueError, match="spec_window"):
        model.apply({"params": params, "cache": cache},
                    jnp.zeros((1, 2), jnp.int32),
                    positions=jnp.zeros((1, 2), jnp.int32),
                    mutable=["cache"])

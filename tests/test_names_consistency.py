"""Static consistency: every state-store table the package touches is
declared in state/names.py — a new table (e.g. TABLE_GOODPUT) cannot
be typo-forked into a parallel name nobody reads.

Pure AST scan over batch_shipyard_tpu/**/*.py; cheap by design (no
imports of the scanned modules, no JAX)."""

import ast
import pathlib

from batch_shipyard_tpu.state import names

PACKAGE = pathlib.Path(names.__file__).resolve().parent.parent

# StateStore methods whose first argument is a table name.
_TABLE_METHODS = {
    "insert_entity", "upsert_entity", "merge_entity", "get_entity",
    "query_entities", "delete_entity", "insert_entities",
}

_DECLARED_ATTRS = {attr for attr in dir(names)
                   if attr.startswith("TABLE_")}
_DECLARED_VALUES = {getattr(names, attr) for attr in _DECLARED_ATTRS}


def _iter_package_sources():
    for path in sorted(PACKAGE.rglob("*.py")):
        yield path, ast.parse(path.read_text(encoding="utf-8"),
                              filename=str(path))


def test_declared_table_values_are_unique():
    assert len(_DECLARED_VALUES) == len(_DECLARED_ATTRS), (
        "two TABLE_* constants in state/names.py share a value")


def test_every_table_literal_is_declared():
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            # Any TABLE_* attribute/name reference must resolve to a
            # declared constant.
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("TABLE_"):
                if node.attr not in _DECLARED_ATTRS:
                    problems.append(
                        f"{rel}:{node.lineno}: undeclared "
                        f"{node.attr}")
            # A string literal passed as the table argument of a
            # store call must be a declared table VALUE.
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in _TABLE_METHODS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str):
                    if first.value not in _DECLARED_VALUES:
                        problems.append(
                            f"{rel}:{node.lineno}: table literal "
                            f"{first.value!r} not declared in "
                            f"state/names.py")
    assert not problems, "\n".join(problems)


def test_goodput_table_declared():
    # The event log's table rides the same registry as every other
    # coordination surface.
    assert names.TABLE_GOODPUT == "goodput"
    assert "TABLE_GOODPUT" in _DECLARED_ATTRS


def test_goodput_program_constants_are_declared():
    """Every PROGRAM_* constant referenced at an emit site resolves
    to a declared constant in goodput/events.py whose value is a
    registered EVENT_KIND — a typo'd phase name cannot silently
    produce events the accounting drops."""
    from batch_shipyard_tpu.goodput import events as gp_events
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("PROGRAM_"):
                value = getattr(gp_events, node.attr, None)
                if value is None:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} not "
                        f"declared in goodput/events.py")
                elif value not in gp_events.EVENT_KINDS:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} value "
                        f"{value!r} missing from EVENT_KINDS")
    assert not problems, "\n".join(problems)


def test_task_state_literals_come_from_the_registry():
    """Every task-state string literal compared against or written to
    a task entity's "state" must be a member of names.TASK_STATES (or
    the auxiliary vocabularies) — a typo'd state ("quarantined" vs
    "quarantine") would silently dodge every terminal-state check in
    the fleet. Scans comparisons (==, in) whose other side mentions
    "state" and dict literals with a "state" key."""
    allowed = (set(names.TASK_STATES) | set(names.NODE_STATES)
               | set(names.AUX_STATES))
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            # {"state": "<literal>"} entity patches.
            if isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if isinstance(key, ast.Constant) and \
                            key.value == "state" and \
                            isinstance(value, ast.Constant) and \
                            isinstance(value.value, str):
                        if value.value not in allowed:
                            problems.append(
                                f"{rel}:{node.lineno}: state "
                                f"literal {value.value!r} not in "
                                f"state/names.py vocabularies")
            # state == "<literal>" / state in ("<literal>", ...)
            if isinstance(node, ast.Compare):
                mentions_state = "state" in ast.dump(node.left).lower()
                if not mentions_state:
                    continue
                for comparator in node.comparators:
                    literals = []
                    if isinstance(comparator, ast.Constant) and \
                            isinstance(comparator.value, str):
                        literals = [comparator.value]
                    elif isinstance(comparator, (ast.Tuple, ast.List,
                                                 ast.Set)):
                        literals = [
                            e.value for e in comparator.elts
                            if isinstance(e, ast.Constant) and
                            isinstance(e.value, str)]
                    for literal in literals:
                        # Upper-case literals are cloud-API enums
                        # (GCE VM states), not our vocabulary.
                        if literal and literal not in allowed and \
                                literal.isidentifier() and \
                                literal == literal.lower():
                            problems.append(
                                f"{rel}:{node.lineno}: state "
                                f"literal {literal!r} not in "
                                f"state/names.py vocabularies")
    assert not problems, "\n".join(problems)


def test_quarantine_and_health_names_declared():
    """PR 5's new vocabulary rides the registry: the quarantined task
    state is terminal (and a TASK_STATE), and the node health columns
    are single-sourced."""
    assert names.TASK_STATE_QUARANTINED == "quarantined"
    assert names.TASK_STATE_QUARANTINED in names.TASK_STATES
    assert names.TASK_STATE_QUARANTINED in names.TERMINAL_TASK_STATES
    assert set(names.TERMINAL_TASK_STATES) <= set(names.TASK_STATES)
    assert names.NODE_COL_HEALTH == "health"
    assert names.NODE_COL_QUARANTINED == "quarantined"


def test_task_and_backoff_event_constants_are_declared():
    """Every TASK_* event constant referenced at an emit site (the
    retry supervisor's TASK_RETRY/TASK_BACKOFF among them) resolves
    to a declared goodput/events.py constant registered in
    EVENT_KINDS, and the backoff category is priced by the
    accounting sweep (not silently dropped into 'unaccounted')."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    problems = []
    event_attrs = {"TASK_QUEUED", "TASK_IMAGE_PULL",
                   "TASK_CONTAINER_START", "TASK_RUNNING",
                   "TASK_RETRY", "TASK_BACKOFF"}
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in event_attrs:
                value = getattr(gp_events, node.attr, None)
                if value is None:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} not "
                        f"declared in goodput/events.py")
                elif value not in gp_events.EVENT_KINDS:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} value "
                        f"{value!r} missing from EVENT_KINDS")
    assert not problems, "\n".join(problems)
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_BACKOFF] == "backoff"
    assert "backoff" in accounting.BADPUT_CATEGORIES


def test_preemption_and_resize_names_declared():
    """PR 10's vocabulary rides the registries: the preempted task
    state is NON-terminal and claimable; every TASK_PREEMPT_* /
    GANG_RESIZE event constant referenced at an emit site resolves to
    a declared goodput/events.py constant registered in EVENT_KINDS;
    the recovery interval is priced as the preemption_recovery badput
    category (never silently 'unaccounted'); and the preempt/resize
    trace spans ride SPAN_KINDS (enforced by the generic SPAN_ scan
    too)."""
    from batch_shipyard_tpu.goodput import accounting
    from batch_shipyard_tpu.goodput import events as gp_events
    from batch_shipyard_tpu.trace import spans as trace_spans
    assert names.TASK_STATE_PREEMPTED == "preempted"
    assert names.TASK_STATE_PREEMPTED in names.TASK_STATES
    assert names.TASK_STATE_PREEMPTED not in \
        names.TERMINAL_TASK_STATES
    assert names.TASK_STATE_PREEMPTED in names.CLAIMABLE_TASK_STATES
    assert set(names.CLAIMABLE_TASK_STATES) <= set(names.TASK_STATES)
    problems = []
    event_attrs = {"TASK_PREEMPT_NOTICE", "TASK_PREEMPT_EXIT",
                   "TASK_PREEMPT_RECOVERY", "GANG_RESIZE"}
    referenced = set()
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    (node.attr in event_attrs
                     or node.attr.startswith("TASK_PREEMPT_")):
                referenced.add(node.attr)
                value = getattr(gp_events, node.attr, None)
                if value is None:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} not "
                        f"declared in goodput/events.py")
                elif value not in gp_events.EVENT_KINDS:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} value "
                        f"{value!r} missing from EVENT_KINDS")
    assert not problems, "\n".join(problems)
    # Every kind of the new family is actually referenced at an emit
    # site — a declared-but-never-emitted kind is dead registry.
    assert event_attrs <= referenced, event_attrs - referenced
    assert accounting._KIND_CATEGORY[
        gp_events.TASK_PREEMPT_RECOVERY] == "preemption_recovery"
    assert "preemption_recovery" in accounting.BADPUT_CATEGORIES
    assert trace_spans.SPAN_PREEMPT in trace_spans.SPAN_KINDS
    assert trace_spans.SPAN_GANG_RESIZE in trace_spans.SPAN_KINDS


def test_chaos_kinds_help_lists_node_preempt_notice():
    """`chaos plan --kinds` (and drill) inline the valid kinds from
    INJECTION_KINDS — the new advance-notice kind must be in the
    registry AND the CLI help must actually derive from it (a
    hardcoded help string would go stale silently)."""
    from batch_shipyard_tpu.chaos.plan import INJECTION_KINDS
    assert "node_preempt_notice" in INJECTION_KINDS
    cli_tree = ast.parse(
        (PACKAGE / "cli" / "main.py").read_text(encoding="utf-8"))
    # Each --kinds option's help is built by joining INJECTION_KINDS.
    joins = 0
    for node in ast.walk(cli_tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join" and node.args and \
                isinstance(node.args[0], ast.Attribute) and \
                node.args[0].attr == "INJECTION_KINDS":
            joins += 1
    assert joins >= 2, (
        "--kinds help no longer derives from INJECTION_KINDS")
    # And the rendered help really names the new kind.
    import click

    from batch_shipyard_tpu.cli import main as cli_main
    ctx = click.Context(cli_main.chaos_plan, info_name="plan")
    # click wraps long help lines mid-token: collapse whitespace
    # before matching.
    rendered = "".join(cli_main.chaos_plan.get_help(ctx).split())
    assert "node_preempt_notice" in rendered


def test_scheduler_scale_workload_dispatched_and_rendered():
    """The 10^5 proof is wired end to end: bench.py dispatches the
    scheduler_scale workload, benchgen reads the committed
    BENCH_scheduler_scale.json artifact, and the artifact itself
    records a complete, partition-exact run of >= 10^5 tasks."""
    import json
    bench_src = (PACKAGE.parent / "bench.py").read_text(
        encoding="utf-8")
    assert '"scheduler_scale" in workloads' in bench_src
    benchgen_src = (PACKAGE.parent / "tools" / "benchgen.py"
                    ).read_text(encoding="utf-8")
    assert "BENCH_scheduler_scale.json" in benchgen_src
    artifact = PACKAGE.parent / "BENCH_scheduler_scale.json"
    assert artifact.exists(), (
        "BENCH_scheduler_scale.json not committed — run "
        "`python bench.py --workloads scheduler_scale`")
    data = json.loads(artifact.read_text(
        encoding="utf-8"))["scheduler_scale"]
    assert data["num_tasks"] >= 100_000
    assert data["completed"] is True
    assert data["goodput"]["partition_exact"] is True


def test_train_workloads_enable_the_compile_cache():
    """Every workload that builds a parallel.train harness must go
    through the compilecache enable hook (compilecache.
    enable_from_args) AND register its flag surface
    (add_compile_cache_args) — a workload that silently opts out of
    the persistent cache pays a cold XLA compile on every node and
    every restart, exactly the badput the warm-start pipeline exists
    to remove (mirrors the no-blocking-checkpoint-save check)."""
    problems = []
    for path in sorted((PACKAGE / "workloads").glob("train_*.py")):
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
        rel = path.relative_to(PACKAGE.parent)
        uses_train = any(
            isinstance(node, ast.ImportFrom) and
            node.module == "batch_shipyard_tpu.parallel" and
            any(alias.name == "train" for alias in node.names)
            for node in ast.walk(tree))
        if not uses_train:
            continue
        calls = {
            node.func.attr
            for node in ast.walk(tree)
            if isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute)}
        for required in ("enable_from_args",
                         "add_compile_cache_args"):
            if required not in calls:
                problems.append(
                    f"{rel}: parallel.train workload never calls "
                    f"compilecache.{required} — it silently opts "
                    f"out of the persistent compile cache")
    assert not problems, "\n".join(problems)


def _tpu_checks_names():
    """CHECKS keys from tools/tpu_checks.py, by AST (dict literal
    keys plus CHECKS["..."] = ... assignments) — no import of the
    TPU harness."""
    path = PACKAGE.parent / "tools" / "tpu_checks.py"
    tree = ast.parse(path.read_text(encoding="utf-8"),
                     filename=str(path))
    names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and \
                        target.id == "CHECKS" and \
                        isinstance(node.value, ast.Dict):
                    names |= {k.value for k in node.value.keys
                              if isinstance(k, ast.Constant)}
                if isinstance(target, ast.Subscript) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "CHECKS" and \
                        isinstance(target.slice, ast.Constant):
                    names.add(target.slice.value)
    return names


def test_kernel_select_names_are_backed_by_tpu_checks():
    """Every validation name the package consults for impl='auto'
    dispatch (kernel_select.resolve_auto / kernel_validated) must be
    a tools/tpu_checks.py CHECKS entry — a typo'd gate name would
    keep a Pallas fast path off forever with no failing check to say
    why (the ring_collectives / dense_decode_int8 gates among
    them)."""
    check_names = _tpu_checks_names()
    assert check_names, "could not parse tpu_checks.CHECKS"
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = (func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name)
                    else None)
            if name not in ("resolve_auto", "kernel_validated"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                check = node.args[0].value
                if check not in check_names:
                    problems.append(
                        f"{rel}:{node.lineno}: kernel_select gate "
                        f"{check!r} has no tools/tpu_checks.py "
                        f"CHECKS entry")
    assert not problems, "\n".join(problems)


def test_benchgen_phase_and_workload_names_exist():
    """Every silicon-proof phase name tools/benchgen.py binds to
    (p.get("phase") == "X") must be record()-ed by
    tools/silicon_proof.py, and every bench workload a silicon-proof
    phase command invokes (--workloads X) must be dispatched by
    bench.py ("X" in workloads) — a renamed phase cannot silently
    turn a docs section or a pipeline phase into a no-op."""
    tools = PACKAGE.parent / "tools"
    benchgen_tree = ast.parse(
        (tools / "benchgen.py").read_text(encoding="utf-8"))
    proof_src = (tools / "silicon_proof.py").read_text(
        encoding="utf-8")
    proof_tree = ast.parse(proof_src)
    bench_tree = ast.parse(
        (PACKAGE.parent / "bench.py").read_text(encoding="utf-8"))

    recorded = set()
    workloads_invoked = set()
    for node in ast.walk(proof_tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "record" and node.args and \
                isinstance(node.args[0], ast.Constant):
            recorded.add(node.args[0].value)
        # ["...", "--workloads", "X", ...] command lists.
        if isinstance(node, ast.List):
            values = [e.value for e in node.elts
                      if isinstance(e, ast.Constant) and
                      isinstance(e.value, str)]
            for i, value in enumerate(values[:-1]):
                if value == "--workloads":
                    workloads_invoked |= {
                        w.strip() for w in values[i + 1].split(",")}

    referenced = set()
    for node in ast.walk(benchgen_tree):
        # p.get("phase") == "X" comparisons.
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Call) and \
                isinstance(node.left.func, ast.Attribute) and \
                node.left.func.attr == "get" and node.left.args and \
                isinstance(node.left.args[0], ast.Constant) and \
                node.left.args[0].value == "phase":
            for comparator in node.comparators:
                if isinstance(comparator, ast.Constant) and \
                        isinstance(comparator.value, str):
                    referenced.add(comparator.value)
    assert referenced, "no phase references found in benchgen.py"
    missing = referenced - recorded
    assert not missing, (
        f"benchgen.py binds to silicon-proof phases {sorted(missing)} "
        f"that tools/silicon_proof.py never records")

    dispatched = set()
    for node in ast.walk(bench_tree):
        # "X" in workloads dispatch checks.
        if isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Constant) and \
                isinstance(node.left.value, str) and \
                len(node.ops) == 1 and \
                isinstance(node.ops[0], ast.In) and \
                isinstance(node.comparators[0], ast.Name) and \
                node.comparators[0].id == "workloads":
            dispatched.add(node.left.value)
    assert dispatched, "no workload dispatch found in bench.py"
    missing = workloads_invoked - dispatched
    assert not missing, (
        f"silicon_proof.py invokes bench workloads {sorted(missing)} "
        f"that bench.py never dispatches")
    # The new kernel phase is wired end to end.
    assert "ring_collectives" in recorded
    assert "ring_collectives" in dispatched


def test_span_kinds_are_declared_in_trace_spans():
    """Every SPAN_* constant referenced at an emit site anywhere in
    the package must resolve to a declared constant in trace/spans.py
    whose value is registered in SPAN_KINDS — a typo'd span kind
    would silently produce spans the exporter drops (the same rule
    the goodput PROGRAM_* constants live under)."""
    from batch_shipyard_tpu.trace import spans as trace_spans
    problems = []
    for path, tree in _iter_package_sources():
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr.startswith("SPAN_"):
                value = getattr(trace_spans, node.attr, None)
                if value is None:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} not "
                        f"declared in trace/spans.py")
                elif value not in trace_spans.SPAN_KINDS:
                    problems.append(
                        f"{rel}:{node.lineno}: {node.attr} value "
                        f"{value!r} missing from SPAN_KINDS")
    assert not problems, "\n".join(problems)
    # The span log's table rides the names registry like every other
    # coordination surface.
    assert names.TABLE_TRACE == "trace"
    assert "TABLE_TRACE" in _DECLARED_ATTRS


def test_trace_and_profile_fleet_actions_are_wired_in_cli():
    """Every fleet trace/profile action (action_trace_* and
    action_jobs_profile) must have a cli/main.py call site — an
    unwired action is dead surface nobody can reach (`shipyard trace
    show|export`, `shipyard jobs profile`)."""
    fleet_tree = ast.parse(
        (PACKAGE / "fleet.py").read_text(encoding="utf-8"))
    actions = {
        node.name for node in ast.walk(fleet_tree)
        if isinstance(node, ast.FunctionDef)
        and (node.name.startswith("action_trace_")
             or node.name == "action_jobs_profile")}
    assert actions, "no trace/profile actions found in fleet.py"
    cli_tree = ast.parse(
        (PACKAGE / "cli" / "main.py").read_text(encoding="utf-8"))
    called = {
        node.func.attr for node in ast.walk(cli_tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "fleet"}
    missing = actions - called
    assert not missing, (
        f"fleet trace/profile actions {sorted(missing)} are not "
        f"wired in cli/main.py")


def test_train_loops_never_call_blocking_checkpoint_save():
    """The train workloads must drive checkpoints through
    checkpoint.TrainCheckpointer (which routes to the async manager
    under --async-checkpoint): a direct blocking ``checkpoint.save``
    in a step loop reintroduces the full-persist stall the zero-stall
    pipeline exists to remove, and skips the stale-step guard."""
    problems = []
    for path in sorted((PACKAGE / "workloads").glob("train_*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"),
                         filename=str(path))
        rel = path.relative_to(PACKAGE.parent)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "save" and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "checkpoint":
                problems.append(
                    f"{rel}:{node.lineno}: direct blocking "
                    f"checkpoint.save() in a train workload — use "
                    f"checkpoint.TrainCheckpointer")
    assert not problems, "\n".join(problems)

"""Dense linear-algebra benchmark: the HPLinpack recipe analog
(/root/reference/recipes/HPLinpack-Infiniband-IntelMPI — solve a dense
system, report FLOP/s), restated for the MXU.

Two phases, both on-device:
  - solve: LU-factorize and solve A x = b at --n (fp32; XLA's blocked
    LU rides the MXU) and report the classic HPL GFLOP/s figure
    (2/3 n^3 + 2 n^2) / t, validated by the HPL residual
    ||Ax-b|| / (||A|| ||x|| n eps);
  - peak: sustained big-matmul GFLOP/s in bf16 and fp32 (the MXU
    ceiling the solve is measured against).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.workloads import distributed


def bench_solve(n: int, iters: int) -> dict:
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(n, n), jnp.float32)
    b = jnp.asarray(rng.randn(n), jnp.float32)
    solve = jax.jit(jnp.linalg.solve)
    x = solve(a, b).block_until_ready()
    start = time.perf_counter()
    for _ in range(iters):
        x = solve(a, b)
    x.block_until_ready()
    elapsed = (time.perf_counter() - start) / iters
    flops = (2.0 / 3.0) * n ** 3 + 2.0 * n ** 2
    # HPL-style scaled residual.
    resid = float(jnp.linalg.norm(a @ x - b) /
                  (jnp.linalg.norm(a) * jnp.linalg.norm(x) * n *
                   np.finfo(np.float32).eps))
    return {"gflops": flops / elapsed / 1e9, "seconds": elapsed,
            "residual": resid}


def bench_peak_matmul(n: int, iters: int, dtype) -> float:
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(n, n), dtype)
    b = jnp.asarray(rng.randn(n, n), dtype)

    @jax.jit
    def chain(a, b):
        # 8 dependent matmuls per call amortize dispatch overhead.
        out = a
        for _ in range(8):
            out = jnp.matmul(out, b,
                             preferred_element_type=jnp.float32
                             ).astype(dtype)
        return out

    chain(a, b).block_until_ready()
    start = time.perf_counter()
    for _ in range(iters):
        out = chain(a, b)
    out.block_until_ready()
    elapsed = (time.perf_counter() - start) / iters
    return 8 * 2.0 * n ** 3 / elapsed / 1e9


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=8192,
                        help="solve dimension")
    parser.add_argument("--peak-n", type=int, default=8192)
    parser.add_argument("--iters", type=int, default=3)
    args = parser.parse_args()
    ctx = distributed.setup()
    solve = bench_solve(args.n, args.iters)
    peak_bf16 = bench_peak_matmul(args.peak_n, args.iters,
                                  jnp.bfloat16)
    peak_f32 = bench_peak_matmul(args.peak_n, args.iters, jnp.float32)
    ok = solve["residual"] < 16.0  # HPL acceptance threshold
    distributed.log(ctx, (
        f"mxu_linpack: n={args.n} {solve['gflops']:.1f} GFLOP/s "
        f"(fp32 LU solve, residual={solve['residual']:.3f} "
        f"{'PASS' if ok else 'FAIL'}), peak matmul "
        f"{peak_bf16:.0f} GFLOP/s bf16 / {peak_f32:.0f} fp32"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

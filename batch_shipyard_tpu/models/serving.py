"""Continuous batching: a slot-based serving engine over the KV-cache
decode path.

ROADMAP item (the reference has no serving story): instead of
generating whole batches in lockstep (models/inference.generate —
every sequence must finish before any slot frees), the engine holds a
fixed pool of decode SLOTS sharing one batched KV cache. Requests
admit into free slots as they arrive (per-slot prefill via a batch-1
scatter into the big cache), every engine step decodes ONE token for
all active slots in a single jitted call, and finished slots free
immediately for the next request — the throughput property
continuous-batching servers (Orca/vLLM-class) are built around.

TPU-first mechanics: the per-slot cache index ([B] int32,
transformer._decode_attend) lets slots sit at different depths in one
[B, T, H, D] cache; per-slot RoPE positions ride the 2-D positions
path; everything is static-shape jitted — admit/emit bookkeeping is
host-side Python, compute is two compiled functions (prefill, step).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.models import inference as inf
from batch_shipyard_tpu.models import transformer as tfm


@dataclasses.dataclass
class Request:
    request_id: str
    prompt: list[int]
    max_new_tokens: int
    eos_id: Optional[int] = None
    # Admission priority among QUEUED requests (higher admits first;
    # ties FIFO). Active slots are never preempted for priority —
    # this orders the wait line, like job.priority orders task
    # queues.
    priority: int = 0


@dataclasses.dataclass
class _Slot:
    request: Optional[Request] = None
    generated: list[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _QueueEntry:
    """A queued request, plus the tokens it had already generated if
    it was preempted (overcommit mode): resumption re-prefills
    prompt + resumed in one pass and continues decoding — the greedy
    continuation is identical to the uninterrupted run."""
    request: Request
    resumed: list[int] = dataclasses.field(default_factory=list)


class ContinuousBatcher:
    """Slot-based continuous batching engine.

    Usage:
        engine = ContinuousBatcher(config, params, num_slots=8,
                                   max_decode_len=2048)
        engine.submit(Request("r1", prompt_ids, max_new_tokens=128))
        while engine.pending():
            for request_id, tokens in engine.step():
                ...  # finished request
    """

    def __init__(self, config: tfm.TransformerConfig, params,
                 num_slots: int, max_decode_len: int,
                 sampling: inf.SamplingConfig = inf.SamplingConfig(),
                 seed: int = 0,
                 kv_page_size: Optional[int] = None,
                 kv_num_pages: Optional[int] = None,
                 overcommit: bool = False,
                 prefill_chunk: Optional[int] = None,
                 on_token: Optional[
                     Callable[[str, int, int], None]] = None):
        """kv_page_size enables the PAGED KV cache (vLLM-style): K/V
        live in a shared kv_num_pages-page pool and slots hold block
        tables covering only their live tokens, so HBM is sized for
        aggregate active context instead of
        num_slots * max_decode_len. kv_num_pages defaults to the
        no-deadlock capacity (num_slots * ceil(max_len/page)).

        Admission policy for a smaller pool:
          - overcommit=False (default): RESERVATION — admission takes
            each request's worst-case page count (prompt +
            max_new_tokens) up front, so decode can never exhaust the
            pool, at the cost of admitting fewer concurrent requests
            than actual usage would allow.
          - overcommit=True: PREEMPTION — admission takes only the
            prompt's pages (+1 headroom); when a decode step needs a
            page and none is free, the active slot with the fewest
            generated tokens is preempted (pages reclaimed, request
            re-queued at the head) and later resumed by re-prefilling
            prompt + already-generated tokens. Short actual
            generations then share a pool far below worst-case.

        prefill_chunk caps the CHUNKED PREFILL segment length: long
        prompts prefill in fixed-size multi-token inserts (each chunk
        attends causally over the cache, so the math is identical to
        one full-sequence pass) — the peak prefill score tensor
        shrinks from O(L * max_decode_len) to
        O(chunk * max_decode_len) (decode-path attention spans the
        full cache width). Compilation stays per length bucket (the
        chunk loop unrolls inside the bucket's jit). Use a power of
        two so chunks divide the power-of-two length buckets
        exactly."""
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
        self.prefill_chunk = prefill_chunk
        self.config = inf.decode_config(config, max_decode_len)
        self.paged = kv_page_size is not None
        self.overcommit = overcommit
        # Observer called as (request_id, token, index) the moment a
        # token is generated (index 0 = the prefill-sampled first
        # token) — the TTFT/TPOT measurement point for serving front
        # ends. Runs on the engine's stepping thread.
        self.on_token = on_token
        self.preemptions = 0
        if overcommit and not self.paged:
            raise ValueError("overcommit requires the paged KV cache "
                             "(kv_page_size)")
        if self.paged:
            if max_decode_len % kv_page_size:
                raise ValueError("max_decode_len must be a multiple "
                                 "of kv_page_size")
            if kv_num_pages is None:
                kv_num_pages = num_slots * (
                    max_decode_len // kv_page_size)
            self.config = dataclasses.replace(
                self.config, kv_page_size=kv_page_size,
                kv_num_pages=kv_num_pages)
            self.page_size = kv_page_size
            self.max_blocks = max_decode_len // kv_page_size
            self._free_pages = list(range(kv_num_pages))
            # Reservation budget: admission reserves each request's
            # WORST-CASE page count up front (prompt + max_new_tokens)
            # so lazy growth during decode can never deadlock two
            # half-grown slots against each other.
            self._avail_pages = kv_num_pages
            self._total_pages = kv_num_pages
            self._slot_reserved = [0] * num_slots
            # The decode step runs the full slot batch, so INACTIVE
            # slots keep writing (masked-on-read) K/V through their
            # block tables. Their tables must therefore never point at
            # allocatable pages: one extra physical SCRATCH page (index
            # kv_num_pages) absorbs those writes, and freed slots'
            # table rows reset to it.
            self._scratch_page = kv_num_pages
            self.config = dataclasses.replace(
                self.config, kv_num_pages=kv_num_pages + 1)
            self._table = np.full((num_slots, self.max_blocks),
                                  self._scratch_page, np.int32)
            self._slot_pages: list[list[int]] = [
                [] for _ in range(num_slots)]
        self.model = tfm.TransformerLM(self.config)
        self.params = params
        self.num_slots = num_slots
        self.max_decode_len = max_decode_len
        self.sampling = sampling
        self.cache = inf.init_cache(self.model, params, num_slots)
        if self.paged:
            # Fresh caches default block tables to zeros (a REAL
            # page); point every slot at the scratch page before any
            # step runs.
            self._push_tables()
        self._slots = [_Slot() for _ in range(num_slots)]
        self._queue: list[_QueueEntry] = []
        self._tokens = jnp.zeros((num_slots, 1), jnp.int32)
        self._positions = jnp.zeros((num_slots,), jnp.int32)
        self._active = jnp.zeros((num_slots,), jnp.bool_)
        self._key = jax.random.PRNGKey(seed)

        model = self.model
        sampling_cfg = self.sampling

        @jax.jit
        def decode_step(params, cache, tokens, positions, active, key):
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, tokens,
                positions=positions[:, None], mutable=["cache"])
            next_tok = inf._sample(logits[:, 0].astype(jnp.float32),
                                   key, sampling_cfg)
            # Inactive slots DO write garbage into their cache rows,
            # and that is fine: a freed row is never read (the
            # per-slot mask excludes other rows) and _admit's prefill
            # rewrites the whole row + index before reuse — restoring
            # the full K/V trees here would double per-token HBM
            # traffic for no observable effect. Only the cheap token/
            # position bookkeeping needs masking.
            next_tok = jnp.where(active, next_tok, tokens[:, 0])
            positions = jnp.where(active, positions + 1, positions)
            return (mutated["cache"], next_tok[:, None], positions,
                    next_tok)

        self._decode_step = decode_step

        # Prefill always runs on a DENSE batch-1 decode model sharing
        # the params; paged mode then scatters its rows into the
        # slot's allocated pages.
        dense_model = tfm.TransformerLM(
            inf.decode_config(config, max_decode_len))
        page = getattr(self, "page_size", 0)

        def dense_prefill(params, prompt, prompt_len):
            """Batch-1 BATCHED prefill over the (bucket-padded) prompt
            [1, L]: the multi-token insert path of
            transformer._decode_attend writes all L cache rows and
            attends causally in MXU-batched passes — prefill
            wall-clock is one forward (or ceil(L/chunk) chunked
            forwards with self.prefill_chunk set, bounding the score
            tensor at O(chunk * max_decode_len)), not L sequential
            micro-steps. Compiles remain one per length bucket.

            prompt_len is DYNAMIC (a traced int32): rows written past
            prompt_len are garbage, but they are masked-on-read
            (key_pos <= idx) and each is overwritten by the decode
            step that first reaches its position, so only the length
            bookkeeping needs the true value. This is what makes L
            bucketable: one compile per BUCKET instead of one per
            distinct prompt length.

            The last-token logits come from the final hidden state at
            prompt_len-1 (return_hidden + a [d, vocab] matvec) so the
            full [L, vocab] fp32 logits tensor never materializes."""
            small = inf.init_cache(dense_model, params, 1)
            total = prompt.shape[1]
            chunk = min(self.prefill_chunk or total, total)
            hiddens = []
            cache = small
            for off in range(0, total, chunk):
                seg = prompt[:, off:off + chunk]
                # Positions are GLOBAL offsets: RoPE for chunk c must
                # match the full-sequence pass exactly.
                h, mut = dense_model.apply(
                    {"params": params, "cache": cache}, seg,
                    return_hidden=True,
                    positions=jnp.arange(
                        off, off + seg.shape[1], dtype=jnp.int32),
                    mutable=["cache"])
                cache = mut["cache"]
                hiddens.append(h)
            hidden = (hiddens[0] if len(hiddens) == 1
                      else jnp.concatenate(hiddens, axis=1))
            last_h = jnp.take(hidden[0], prompt_len - 1,
                              axis=0)                       # [d]
            embedding = params["embed"]["embedding"]
            last = jnp.dot(embedding.astype(jnp.float32),
                           last_h.astype(jnp.float32))      # [vocab]
            return cache, last

        @jax.jit
        def prefill(params, cache, slot, prompt, prompt_len):
            """Fill ONE slot's cache region from a prompt [1, L]
            (batch-1 forward, scattered into the slot row), returning
            the last-token logits for the first sample. The small
            cache's write index ran to L (the padded length); the
            slot's index is corrected to the true prompt_len."""
            small, last = dense_prefill(params, prompt, prompt_len)

            def scatter(big, sm, path_key):
                if path_key == "index":
                    return big.at[slot].set(prompt_len)
                return big.at[slot].set(sm[0])

            cache = jax.tree_util.tree_map_with_path(
                lambda kp, big, sm: scatter(
                    big, sm, kp[-1].key if hasattr(kp[-1], "key")
                    else str(kp[-1])),
                cache, small)
            return cache, last

        @jax.jit
        def prefill_paged(params, cache, slot, prompt, table_row,
                          prompt_len):
            """Paged variant: dense batch-1 prefill, rows scattered
            page-by-page into the slot's allocated pages; the slot's
            block-table row and length are set in every layer's cache
            copy. Full pages are written unconditionally: blocks past
            the allocation point at the scratch page (which absorbs
            padded-garbage writes), and partial-page garbage is
            masked-on-read via the true length."""
            small, last = dense_prefill(params, prompt, prompt_len)
            # Bucket blocks, static (ceil: a bucket smaller than one
            # page still needs its first page written; the small
            # cache has max_decode_len >= n_blocks*page rows).
            n_blocks = -(-prompt.shape[1] // page)

            def scatter(big, sm):
                if isinstance(big, dict) and "k_pages" in big:
                    kp, vp = big["k_pages"], big["v_pages"]
                    for b in range(n_blocks):
                        krows = sm["k"][0, b * page:(b + 1) * page]
                        vrows = sm["v"][0, b * page:(b + 1) * page]
                        kp = kp.at[table_row[b]].set(
                            krows.astype(kp.dtype))
                        vp = vp.at[table_row[b]].set(
                            vrows.astype(vp.dtype))
                    out = {
                        "k_pages": kp, "v_pages": vp,
                        "block_table":
                            big["block_table"].at[slot].set(table_row),
                        "length":
                            big["length"].at[slot].set(prompt_len),
                    }
                    if "k_page_scales" in big:
                        # int8 pool: the dense prefill cache is int8
                        # too (same kv_cache_dtype), so its rows and
                        # scales route straight into the page pool.
                        ksc = big["k_page_scales"]
                        vsc = big["v_page_scales"]
                        for b in range(n_blocks):
                            ksc = ksc.at[table_row[b]].set(
                                sm["k_scale"][0,
                                              b * page:(b + 1) * page])
                            vsc = vsc.at[table_row[b]].set(
                                sm["v_scale"][0,
                                              b * page:(b + 1) * page])
                        out["k_page_scales"] = ksc
                        out["v_page_scales"] = vsc
                    return out
                return {key: scatter(big[key], sm[key]) for key in big}

            return scatter(cache, small), last

        self._prefill = prefill
        self._prefill_paged = prefill_paged

    # ------------------------------ public -----------------------------

    def submit(self, request: Request) -> None:
        if request.max_new_tokens < 1:
            raise ValueError(
                f"{request.request_id}: max_new_tokens must be >= 1")
        if not request.prompt:
            raise ValueError(
                f"{request.request_id}: prompt must be non-empty")
        if self.paged:
            worst = -(-(len(request.prompt) + request.max_new_tokens)
                      // self.page_size)
            if worst > self._total_pages:
                raise ValueError(
                    f"{request.request_id}: worst-case page need "
                    f"{worst} exceeds the pool ({self._total_pages} "
                    f"pages) — it could never admit")
        if len(request.prompt) + request.max_new_tokens > \
                self.max_decode_len:
            raise ValueError(
                f"{request.request_id}: prompt+generation "
                f"{len(request.prompt)}+{request.max_new_tokens} "
                f"exceeds max_decode_len {self.max_decode_len}")
        self._enqueue(_QueueEntry(request))

    def pending(self) -> int:
        return len(self._queue) + sum(
            1 for s in self._slots if s.request is not None)

    def cancel(self, request_id: str) -> bool:
        """Abort a queued or actively-decoding request (the vLLM-class
        abort operation). Queued entries are removed; an active slot
        is freed immediately (its pages return to the pool). Must be
        called from the engine's stepping thread — it mutates slot
        state like step() does. Returns False when the id is unknown
        (already finished)."""
        for k, entry in enumerate(self._queue):
            if entry.request.request_id == request_id:
                del self._queue[k]
                return True
        for i, slot in enumerate(self._slots):
            if slot.request is not None and \
                    slot.request.request_id == request_id:
                self._free_slot(i)
                return True
        return False

    def step(self) -> list[tuple[str, list[int]]]:
        """Admit queued requests into free slots, decode one token for
        every active slot, emit finished requests."""
        self._admit()
        # Slots whose prefill-sampled first token already satisfied the
        # request (max_new_tokens == 1 or immediate eos) emit without a
        # decode step.
        emitted: list[tuple[str, list[int]]] = []
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None or not slot.generated:
                continue
            last = slot.generated[-1]
            if (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and last == req.eos_id)):
                emitted.append((req.request_id, list(slot.generated)))
                self._free_slot(i)
        if not any(s.request is not None for s in self._slots):
            return emitted
        if self.paged:
            self._grow_pages()
        self._key, step_key = jax.random.split(self._key)
        self.cache, self._tokens, self._positions, next_tok = \
            self._decode_step(self.params, self.cache, self._tokens,
                              self._positions, self._active, step_key)
        next_host = np.asarray(next_tok)
        for i, slot in enumerate(self._slots):
            req = slot.request
            if req is None:
                continue
            token = int(next_host[i])
            slot.generated.append(token)
            if self.on_token is not None:
                self.on_token(req.request_id, token,
                              len(slot.generated) - 1)
            done = (len(slot.generated) >= req.max_new_tokens or
                    (req.eos_id is not None and token == req.eos_id))
            if done:
                emitted.append((req.request_id, list(slot.generated)))
                self._free_slot(i)
        return emitted

    def _free_slot(self, i: int) -> None:
        self._slots[i] = _Slot()
        self._active = self._active.at[i].set(False)
        if self.paged:
            self._free_pages.extend(self._slot_pages[i])
            self._slot_pages[i] = []
            self._avail_pages += self._slot_reserved[i]
            self._slot_reserved[i] = 0
            # The freed slot keeps decoding (masked) in the full-batch
            # step: its table must stop referencing returned pages
            # BEFORE they are reallocated.
            self._table[i] = self._scratch_page
            self._push_tables()

    def _grow_pages(self) -> None:
        """Allocate a fresh page for any active slot whose NEXT write
        starts a new block, and push the updated tables into every
        layer's cache copy. In overcommit mode an empty free list
        preempts a victim instead of raising."""
        positions = np.asarray(self._positions)
        active = np.asarray(self._active).copy()
        changed = False
        for i in range(self.num_slots):
            if not active[i] or self._slots[i].request is None:
                continue
            pos = int(positions[i])
            if pos % self.page_size != 0:
                continue
            block = pos // self.page_size
            if block < len(self._slot_pages[i]):
                continue  # prefill already covers this block
            while not self._free_pages:
                if not self.overcommit:
                    raise RuntimeError(
                        "paged KV pool exhausted mid-decode; size "
                        "kv_num_pages >= num_slots * max_decode_len /"
                        " page_size to rule this out, or enable "
                        "overcommit=True for preemption")
                victim = self._preempt(exclude=i)
                active[victim] = False
            pagenum = self._free_pages.pop()
            self._slot_pages[i].append(pagenum)
            self._table[i, block] = pagenum
            changed = True
        if changed:
            self._push_tables()

    def _preempt(self, exclude: int) -> int:
        """Evict the active slot with the fewest generated tokens
        (cheapest re-prefill), reclaim its pages, and re-queue its
        request AT THE HEAD with its generated-so-far tokens so
        resumption re-prefills prompt+generated and continues — the
        greedy continuation is unchanged. Returns the victim index."""
        candidates = [
            j for j in range(self.num_slots)
            if j != exclude and self._slots[j].request is not None]
        if not candidates:
            raise RuntimeError(
                "paged KV pool exhausted with no preemptible slot — "
                "a single request's live context exceeds the pool")
        victim = min(candidates,
                     key=lambda j: len(self._slots[j].generated))
        slot = self._slots[victim]
        # Preempted work resumes at the HEAD of its own priority
        # class: ahead of waiting peers (it owns partial progress) but
        # never ahead of strictly higher-priority entries — a plain
        # head insert would let a low-priority victim starve a queued
        # high-priority request under sustained page pressure.
        entry = _QueueEntry(slot.request, list(slot.generated))
        pos = 0
        while (pos < len(self._queue) and
               self._queue[pos].request.priority >
               slot.request.priority):
            pos += 1
        self._queue.insert(pos, entry)
        self.preemptions += 1
        self._free_slot(victim)
        return victim

    def _push_tables(self) -> None:
        """Write the canonical block table into every layer's cache
        copy."""
        table = jnp.asarray(self._table)

        def push(leaf_dict):
            if isinstance(leaf_dict, dict) and \
                    "block_table" in leaf_dict:
                return {**leaf_dict, "block_table": table}
            if isinstance(leaf_dict, dict):
                return {k: push(v) for k, v in leaf_dict.items()}
            return leaf_dict

        self.cache = push(self.cache)

    # ----------------------------- internal ----------------------------

    def _bucket_length(self, n: int) -> int:
        """Round a prompt length up to its compile bucket (the next
        power of two, floored at 16, capped at max_decode_len): one
        prefill compile per bucket instead of per distinct length."""
        bucket = 16
        while bucket < n:
            bucket *= 2
        return min(bucket, self.max_decode_len)

    def _enqueue(self, entry: "_QueueEntry") -> None:
        """Insert keeping the queue sorted by descending priority,
        FIFO within a priority class."""
        priority = entry.request.priority
        for k in range(len(self._queue) - 1, -1, -1):
            if self._queue[k].request.priority >= priority:
                self._queue.insert(k + 1, entry)
                return
        self._queue.insert(0, entry)

    def _admit(self) -> None:
        for i, slot in enumerate(self._slots):
            if slot.request is not None or not self._queue:
                continue
            entry = self._queue[0]
            req = entry.request
            # Resumed (preempted) requests re-prefill prompt + what
            # they had already generated, in one batched pass.
            tokens = req.prompt + entry.resumed
            bucket = self._bucket_length(len(tokens))
            padded = tokens + [0] * (bucket - len(tokens))
            prompt = jnp.asarray([padded], jnp.int32)
            if self.paged:
                blocks_needed = -(-len(tokens) // self.page_size)
                remaining = req.max_new_tokens - len(entry.resumed)
                worst = -(-(len(tokens) + remaining)
                          // self.page_size)
                if self.overcommit:
                    # Take only the prompt's pages (+1 block of
                    # decode headroom against immediate re-thrash);
                    # exhaustion during decode preempts.
                    want = min(blocks_needed + (1 if remaining else 0),
                               worst)
                    if len(self._free_pages) < want:
                        break
                else:
                    if self._avail_pages < worst:
                        # Not enough budget for this request's worst
                        # case: wait for frees rather than risking a
                        # mid-decode exhaustion deadlock between
                        # half-grown slots.
                        break
                    self._avail_pages -= worst
                    self._slot_reserved[i] = worst
                self._queue.pop(0)
                pages = [self._free_pages.pop()
                         for _ in range(blocks_needed)]
                self._slot_pages[i] = pages
                row = np.full((self.max_blocks,), self._scratch_page,
                              np.int32)
                row[:blocks_needed] = pages
                self._table[i] = row
                self.cache, last_logits = self._prefill_paged(
                    self.params, self.cache, i, prompt,
                    jnp.asarray(row), len(tokens))
            else:
                self._queue.pop(0)
                self.cache, last_logits = self._prefill(
                    self.params, self.cache, i, prompt, len(tokens))
            self._key, sample_key = jax.random.split(self._key)
            first = inf._sample(
                last_logits[None].astype(jnp.float32), sample_key,
                self.sampling)
            # The prefill-sampled token IS the next generated token.
            self._slots[i] = _Slot(
                request=req,
                generated=entry.resumed + [int(first[0])])
            if self.on_token is not None:
                self.on_token(req.request_id, int(first[0]),
                              len(entry.resumed))
            self._tokens = self._tokens.at[i, 0].set(first[0])
            self._positions = self._positions.at[i].set(len(tokens))
            self._active = self._active.at[i].set(True)

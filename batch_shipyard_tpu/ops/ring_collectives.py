"""On-chip ring collectives: async-DMA Pallas kernels for the ICI.

The flash-ring sequence-parallel path (ops/ring_attention.py) rotates
KV shards with ``lax.ppermute`` and leaves compute/communication
overlap to XLA's scheduler. These kernels take that overlap on-chip:
``pltpu.make_async_remote_copy`` moves the neighbor transfer over the
adjacent ICI link with explicit send/recv DMA semaphores, and the
kernels are double-buffered — two communication slots alternate so the
transfer for ring step t+1 is in flight while step t's local work
(output copy-out for all-gather, the additive accumulate for
reduce-scatter) executes. A regular "capacity" semaphore handshake
releases a slot to the upstream neighbor only after it has been both
copied out and forwarded, which is what makes reusing a slot every
other step safe (the MLPerf pod-scaling recipe: overlap the ring hop
with the local compute, arxiv 1909.09756).

Three kernel families:

  - ``ring_all_gather`` / ``ring_reduce_scatter``: drop-in ring
    equivalents of ``lax.all_gather`` / ``lax.psum_scatter(tiled)``
    over one mesh axis, for shard_map callers on TPU silicon.
  - ``ring_permute_pair``: one ring rotation of a (K, V) shard pair —
    the ``impl='pallas_dma'`` tier of ring attention. custom_vjp: the
    transpose of a +1 ring shift is the -1 ring shift, so the scan'd
    ring body stays differentiable end to end.
  - ``ring_all_gather_virtual`` / ``ring_reduce_scatter_virtual``:
    the SAME step schedule executed over virtual ring members resident
    on one device, with local async DMA copies standing in for the
    remote ones. Pallas interpret mode aborts inside shard_map on CPU
    (see ring_attention.py), so these are what tier-1 exercises — and
    what tools/tpu_checks.py compiles on a single real chip to prove
    the Mosaic DMA/semaphore lowering before the multi-chip path is
    allowed on 'auto' (KERNEL_VALIDATION.json, check name
    ``ring_collectives``).

Shared schedule arithmetic lives in ``ag_source_shard`` /
``rs_chunk_index`` so the real and virtual kernels cannot drift.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.utils.compat import shard_map

# Distinct barrier-semaphore ids per collective kernel family (the
# Mosaic barrier semaphore is global per collective_id; these kernels
# never run concurrently with each other's id).
_CID_PERMUTE_FWD = 11
_CID_PERMUTE_BWD = 12
_CID_ALL_GATHER = 13
_CID_REDUCE_SCATTER = 14


# ---------------------- schedule arithmetic ---------------------------

def ag_source_shard(my_idx, step, ring: int):
    """All-gather: the shard received at ring step `step` (0-based) on
    device `my_idx` is the one originally held by this device."""
    return (my_idx - step - 1) % ring


def rs_chunk_index(my_idx, step, ring: int):
    """Reduce-scatter: the chunk whose partial arrives at device
    `my_idx` at step `step` (the device adds its local contribution
    for that chunk on receipt). Initial send (step -1) is the device's
    own chunk (my_idx - 1) % ring; after ring-1 steps the device holds
    the fully reduced chunk my_idx — the lax.psum_scatter(tiled)
    layout."""
    return (my_idx - step - 2) % ring


def _neighbor_coords(axis_name: str, mesh_axis_names, target_idx):
    """MESH-coordinate device id for a ring neighbor: the ring axis
    takes the target index, every other manual mesh axis keeps this
    device's own coordinate."""
    return tuple(
        target_idx if name == axis_name else jax.lax.axis_index(name)
        for name in mesh_axis_names)


def _neighbor_barrier(axis_name: str, mesh_axis_names, left, right):
    """Block until both ring neighbors have entered the kernel — no
    remote DMA may land in a buffer whose kernel hasn't started."""
    barrier = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(
        barrier, 1, device_id=_neighbor_coords(
            axis_name, mesh_axis_names, left),
        device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_signal(
        barrier, 1, device_id=_neighbor_coords(
            axis_name, mesh_axis_names, right),
        device_id_type=pltpu.DeviceIdType.MESH)
    pltpu.semaphore_wait(barrier, 2)


# ---------------------- ring permute (KV rotation) --------------------

def _ring_permute_kernel(k_ref, v_ref, k_out, v_out, send_sem,
                         recv_sem, *, axis_name: str, mesh_axis_names,
                         ring: int, shift: int):
    """Send this device's K/V shard `shift` hops around the ring; the
    two transfers share the link concurrently (both DMAs in flight
    before either wait)."""
    my = jax.lax.axis_index(axis_name)
    dst = jax.lax.rem(my + shift + ring, ring)
    left = jax.lax.rem(my - 1 + ring, ring)
    right = jax.lax.rem(my + 1, ring)
    _neighbor_barrier(axis_name, mesh_axis_names, left, right)
    dst_coords = _neighbor_coords(axis_name, mesh_axis_names, dst)
    rdma_k = pltpu.make_async_remote_copy(
        src_ref=k_ref, dst_ref=k_out, send_sem=send_sem.at[0],
        recv_sem=recv_sem.at[0], device_id=dst_coords,
        device_id_type=pltpu.DeviceIdType.MESH)
    rdma_v = pltpu.make_async_remote_copy(
        src_ref=v_ref, dst_ref=v_out, send_sem=send_sem.at[1],
        recv_sem=recv_sem.at[1], device_id=dst_coords,
        device_id_type=pltpu.DeviceIdType.MESH)
    rdma_k.start()
    rdma_v.start()
    rdma_k.wait()
    rdma_v.wait()


def _ring_permute_call(k, v, axis_name: str, mesh_axis_names,
                       ring: int, shift: int, collective_id: int):
    return pl.pallas_call(
        functools.partial(
            _ring_permute_kernel, axis_name=axis_name,
            mesh_axis_names=tuple(mesh_axis_names), ring=ring,
            shift=shift),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,))],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=collective_id),
    )(k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def ring_permute_pair(k, v, axis_name: str, mesh_axis_names,
                      ring: int):
    """One +1 ring rotation of the (K, V) pair via async remote DMA —
    the pallas_dma replacement for the two lax.ppermutes in the flash
    ring body. Call inside shard_map on a TPU mesh only (gated by
    kernel_select validation; see ring_attention.resolve_ring_impl)."""
    if ring == 1:
        return k, v
    return _ring_permute_call(k, v, axis_name, mesh_axis_names, ring,
                              shift=1, collective_id=_CID_PERMUTE_FWD)


def _ring_permute_fwd(k, v, axis_name, mesh_axis_names, ring):
    return ring_permute_pair(k, v, axis_name, mesh_axis_names,
                             ring), None


def _ring_permute_bwd(axis_name, mesh_axis_names, ring, _res, grads):
    g_k, g_v = grads
    if ring == 1:
        return g_k, g_v
    # Transpose of the +1 shift: cotangents travel one hop the other
    # way (y_i = x_{i-1}  =>  dx_j = dy_{j+1}).
    return _ring_permute_call(g_k, g_v, axis_name, mesh_axis_names,
                              ring, shift=-1,
                              collective_id=_CID_PERMUTE_BWD)


ring_permute_pair.defvjp(_ring_permute_fwd, _ring_permute_bwd)


# ---------------------- ring all-gather -------------------------------

def _ring_all_gather_kernel(x_ref, o_ref, comm_ref, send_sem,
                            recv_sem, local_sem, capacity_sem, *,
                            axis_name: str, mesh_axis_names,
                            ring: int):
    """Per-device body: forward the chunk received at step t-1 while
    step t's send/recv DMAs are in flight (double-buffered slots s/r),
    releasing each slot to the upstream neighbor via capacity_sem only
    once it is copied out AND resent."""
    chunk = x_ref.shape[0]
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, ring)
    left = jax.lax.rem(my - 1 + ring, ring)
    # Own shard -> its output row and the first send slot.
    cp_out = pltpu.make_async_copy(
        x_ref, o_ref.at[pl.ds(my * chunk, chunk)], local_sem)
    cp_out.start()
    cp_seed = pltpu.make_async_copy(x_ref, comm_ref.at[0],
                                    recv_sem.at[0])
    cp_seed.start()
    cp_out.wait()
    cp_seed.wait()
    _neighbor_barrier(axis_name, mesh_axis_names, left, right)
    left_coords = _neighbor_coords(axis_name, mesh_axis_names, left)
    right_coords = _neighbor_coords(axis_name, mesh_axis_names, right)
    for step in range(ring - 1):
        slot, nxt = step % 2, (step + 1) % 2
        if step > 0:
            # The right neighbor freed the slot we are about to
            # overwrite on it (copied out + resent).
            pltpu.semaphore_wait(capacity_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[slot], dst_ref=comm_ref.at[nxt],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[nxt],
            device_id=right_coords,
            device_id_type=pltpu.DeviceIdType.MESH)
        rdma.start()
        if step > 0:
            # Overlap: while the step-t transfer flies, copy the chunk
            # received at step t-1 (sitting in `slot`, which the send
            # DMA is only READING) into its output row.
            src = ag_source_shard(my, step - 1, ring)
            cp = pltpu.make_async_copy(
                comm_ref.at[slot],
                o_ref.at[pl.ds(src * chunk, chunk)], local_sem)
            cp.start()
            cp.wait()
        rdma.wait()
        if step < ring - 2:
            pltpu.semaphore_signal(
                capacity_sem, 1, device_id=left_coords,
                device_id_type=pltpu.DeviceIdType.MESH)
    src = ag_source_shard(my, ring - 2, ring)
    cp = pltpu.make_async_copy(
        comm_ref.at[(ring - 1) % 2],
        o_ref.at[pl.ds(src * chunk, chunk)], local_sem)
    cp.start()
    cp.wait()


def _ring_all_gather_local(x, *, axis_name: str, mesh_axis_names,
                           ring: int):
    chunk = x.shape[0]
    out, _comm = pl.pallas_call(
        functools.partial(
            _ring_all_gather_kernel, axis_name=axis_name,
            mesh_axis_names=tuple(mesh_axis_names), ring=ring),
        out_shape=(
            jax.ShapeDtypeStruct((ring * chunk,) + x.shape[1:],
                                 x.dtype),
            jax.ShapeDtypeStruct((2, chunk) + x.shape[1:], x.dtype),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.REGULAR],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_CID_ALL_GATHER),
    )(x)
    return out


def ring_all_gather(x, mesh: Mesh, axis_name: str = "sp"):
    """lax.all_gather equivalent over `axis_name` via the async-DMA
    ring kernel. x: global array with dim 0 sharded over the axis;
    returns the gathered (replicated) global array — numerically the
    identity on x, which is exactly what the parity check exploits."""
    ring = mesh.shape[axis_name]
    body = functools.partial(
        _ring_all_gather_local, axis_name=axis_name,
        mesh_axis_names=mesh.axis_names, ring=ring)
    fn = shard_map(body, mesh=mesh, in_specs=P(axis_name),
                   out_specs=P(None), check_vma=False)
    return fn(x)


# ---------------------- ring reduce-scatter ---------------------------

def _ring_reduce_scatter_kernel(x_ref, o_ref, comm_ref, send_sem,
                                recv_sem, local_sem, capacity_sem,
                                acc_vmem, add_vmem, *,
                                axis_name: str, mesh_axis_names,
                                ring: int, chunk: int):
    """Per-device body: each step forwards the partial for one chunk
    and folds the local contribution into the arriving partial. The
    additive accumulate runs in VMEM while this device's own send DMA
    is still in flight (wait_recv before the add, wait_send after)."""
    my = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(my + 1, ring)
    left = jax.lax.rem(my - 1 + ring, ring)
    # Seed slot 0 with the local chunk this device forwards first.
    c0 = rs_chunk_index(my, -1, ring)
    cp = pltpu.make_async_copy(
        x_ref.at[pl.ds(c0 * chunk, chunk)], comm_ref.at[0],
        local_sem)
    cp.start()
    cp.wait()
    _neighbor_barrier(axis_name, mesh_axis_names, left, right)
    left_coords = _neighbor_coords(axis_name, mesh_axis_names, left)
    right_coords = _neighbor_coords(axis_name, mesh_axis_names, right)
    for step in range(ring - 1):
        slot, nxt = step % 2, (step + 1) % 2
        if step > 0:
            pltpu.semaphore_wait(capacity_sem, 1)
        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[slot], dst_ref=comm_ref.at[nxt],
            send_sem=send_sem.at[slot], recv_sem=recv_sem.at[nxt],
            device_id=right_coords,
            device_id_type=pltpu.DeviceIdType.MESH)
        rdma.start()
        # Prefetch the local contribution for the incoming partial
        # while both ring DMAs fly.
        c = rs_chunk_index(my, step, ring)
        cp_local = pltpu.make_async_copy(
            x_ref.at[pl.ds(c * chunk, chunk)], add_vmem, local_sem)
        cp_local.start()
        rdma.wait_recv()
        cp_recv = pltpu.make_async_copy(comm_ref.at[nxt], acc_vmem,
                                        local_sem)
        cp_local.wait()
        cp_recv.start()
        cp_recv.wait()
        # The add overlaps this device's own send (waited below).
        acc_vmem[...] = acc_vmem[...] + add_vmem[...]
        if step < ring - 2:
            cp_back = pltpu.make_async_copy(acc_vmem,
                                            comm_ref.at[nxt],
                                            local_sem)
        else:
            cp_back = pltpu.make_async_copy(acc_vmem, o_ref,
                                            local_sem)
        cp_back.start()
        cp_back.wait()
        rdma.wait_send()
        if step < ring - 2:
            pltpu.semaphore_signal(
                capacity_sem, 1, device_id=left_coords,
                device_id_type=pltpu.DeviceIdType.MESH)


def _ring_reduce_scatter_local(x, *, axis_name: str, mesh_axis_names,
                               ring: int):
    if x.shape[0] % ring:
        raise ValueError(
            f"reduce-scatter dim 0 ({x.shape[0]}) must be divisible "
            f"by the ring size {ring}")
    chunk = x.shape[0] // ring
    out, _comm = pl.pallas_call(
        functools.partial(
            _ring_reduce_scatter_kernel, axis_name=axis_name,
            mesh_axis_names=tuple(mesh_axis_names), ring=ring,
            chunk=chunk),
        out_shape=(
            jax.ShapeDtypeStruct((chunk,) + x.shape[1:], x.dtype),
            jax.ShapeDtypeStruct((2, chunk) + x.shape[1:], x.dtype),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.REGULAR,
                        pltpu.VMEM((chunk,) + x.shape[1:], x.dtype),
                        pltpu.VMEM((chunk,) + x.shape[1:], x.dtype)],
        compiler_params=pltpu.TPUCompilerParams(
            collective_id=_CID_REDUCE_SCATTER),
    )(x)
    return out


def ring_reduce_scatter(x, mesh: Mesh, axis_name: str = "sp"):
    """lax.psum_scatter(tiled) equivalent: x global [ring, ring*chunk,
    ...] with dim 0 sharded over the axis (each device contributes one
    full row); returns the global [ring*chunk, ...] reduced-scattered
    result, i.e. jnp.sum(x, axis=0)."""
    ring = mesh.shape[axis_name]
    body = functools.partial(
        _ring_reduce_scatter_local, axis_name=axis_name,
        mesh_axis_names=mesh.axis_names, ring=ring)

    def per_device(x_local):
        return body(x_local[0])

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=P(axis_name, None),
                   out_specs=P(axis_name), check_vma=False)
    return fn(x)


# ---------------------- virtual (single-device) rings -----------------

def _virtual_all_gather_kernel(x_ref, o_ref, comm_ref, sems, *,
                               ring: int):
    """All virtual ring members resident on one device: identical slot
    schedule, with local async DMA copies standing in for the remote
    ones (every per-step transfer is started before any is waited,
    and the previous step's chunk is copied out while they fly)."""
    chunk = x_ref.shape[1]
    for i in range(ring):
        o_ref[i, pl.ds(i * chunk, chunk), :] = x_ref[i]
        comm_ref[i, 0] = x_ref[i]
    for step in range(ring - 1):
        slot, nxt = step % 2, (step + 1) % 2
        dmas = [pltpu.make_async_copy(
            comm_ref.at[i, slot],
            comm_ref.at[(i + 1) % ring, nxt],
            sems.at[(i + 1) % ring]) for i in range(ring)]
        for dma in dmas:
            dma.start()
        if step > 0:
            for i in range(ring):
                src = ag_source_shard(i, step - 1, ring)
                o_ref[i, pl.ds(src * chunk, chunk), :] = (
                    comm_ref[i, slot])
        for dma in dmas:
            dma.wait()
    for i in range(ring):
        src = ag_source_shard(i, ring - 2, ring)
        o_ref[i, pl.ds(src * chunk, chunk), :] = (
            comm_ref[i, (ring - 1) % 2])


def ring_all_gather_virtual(x_shards, interpret: bool = False):
    """Run the ring all-gather schedule over `ring` virtual members on
    ONE device. x_shards: [ring, chunk, feat]; returns [ring,
    ring*chunk, feat] where row i is what ring member i would hold —
    every row must equal the concatenation of the shards."""
    ring, chunk = x_shards.shape[0], x_shards.shape[1]
    if ring < 2:
        raise ValueError(f"virtual ring needs >= 2 members, got {ring}")
    return pl.pallas_call(
        functools.partial(_virtual_all_gather_kernel, ring=ring),
        out_shape=jax.ShapeDtypeStruct(
            (ring, ring * chunk) + x_shards.shape[2:], x_shards.dtype),
        scratch_shapes=[
            pltpu.VMEM((ring, 2, chunk) + x_shards.shape[2:],
                       x_shards.dtype),
            pltpu.SemaphoreType.DMA((ring,))],
        interpret=interpret,
    )(x_shards)


def _virtual_reduce_scatter_kernel(x_ref, o_ref, comm_ref, sems, *,
                                   ring: int):
    chunk = x_ref.shape[1] // ring
    for i in range(ring):
        c0 = rs_chunk_index(i, -1, ring)
        comm_ref[i, 0] = x_ref[i, pl.ds(c0 * chunk, chunk), :]
    for step in range(ring - 1):
        slot, nxt = step % 2, (step + 1) % 2
        dmas = [pltpu.make_async_copy(
            comm_ref.at[i, slot],
            comm_ref.at[(i + 1) % ring, nxt],
            sems.at[(i + 1) % ring]) for i in range(ring)]
        for dma in dmas:
            dma.start()
        for dma in dmas:
            dma.wait()
        for i in range(ring):
            c = rs_chunk_index(i, step, ring)
            comm_ref[i, nxt] = (comm_ref[i, nxt] +
                                x_ref[i, pl.ds(c * chunk, chunk), :])
    for i in range(ring):
        o_ref[i] = comm_ref[i, (ring - 1) % 2]


def ring_reduce_scatter_virtual(x_rows, interpret: bool = False):
    """Run the ring reduce-scatter schedule over `ring` virtual
    members on ONE device. x_rows: [ring, ring*chunk, feat] (row i is
    member i's full contribution); returns [ring, chunk, feat] where
    row i is member i's reduced chunk — concatenated over i this is
    jnp.sum(x_rows, axis=0), the psum_scatter(tiled) result."""
    ring = x_rows.shape[0]
    if ring < 2:
        raise ValueError(f"virtual ring needs >= 2 members, got {ring}")
    if x_rows.shape[1] % ring:
        raise ValueError(
            f"row length {x_rows.shape[1]} must be divisible by the "
            f"ring size {ring}")
    chunk = x_rows.shape[1] // ring
    return pl.pallas_call(
        functools.partial(_virtual_reduce_scatter_kernel, ring=ring),
        out_shape=jax.ShapeDtypeStruct(
            (ring, chunk) + x_rows.shape[2:], x_rows.dtype),
        scratch_shapes=[
            pltpu.VMEM((ring, 2, chunk) + x_rows.shape[2:],
                       x_rows.dtype),
            pltpu.SemaphoreType.DMA((ring,))],
        interpret=interpret,
    )(x_rows)

"""Slurm elastic burst: power-save Resume/Suspend programs backed by
the framework's pools.

Reference analog: slurm/slurm.py (1472 LoC) — the controller-side
daemon implementing Slurm power-save hooks (slurm.conf:101-103
ResumeProgram/SuspendProgram/ResumeFailProgram): resume adds Batch
nodes to a pool and waits for a host-assignment handshake through
tables/queues (process_resume_action :969,
wait_for_host_assignment_entities :604); suspend removes them (:1044);
an idle-node reaper reclaims capacity (daemon_processor :1353).

TPU-native mapping: a Slurm elastic partition maps to a pool; resuming
N slurm nodes grows the pool by the needed slices and records
host-assignment entities (slurm hostname -> pool node) for the
generated slurm.conf's NodeName entries; suspend shrinks. The same
storage-mediated handshake makes this fully unit-testable.

Entry points (wired into slurm.conf by generate_slurm_conf):
  python -m batch_shipyard_tpu.slurm.burst resume  <hostlist>
  python -m batch_shipyard_tpu.slurm.burst suspend <hostlist>
"""

from __future__ import annotations

import math
import re
import time
from typing import Optional

from batch_shipyard_tpu.config.settings import PoolSettings
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError, StateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def expand_hostlist(hostlist: str) -> list[str]:
    """Expand a slurm hostlist like 'tpu-[0-3,7]' into hostnames."""
    match = re.fullmatch(r"([a-zA-Z0-9_.-]+?)\[([0-9,\-]+)\]", hostlist)
    if not match:
        return [h for h in hostlist.split(",") if h]
    prefix, ranges = match.groups()
    hosts = []
    for part in ranges.split(","):
        if "-" in part:
            lo, hi = part.split("-")
            hosts.extend(f"{prefix}{i}" for i in
                         range(int(lo), int(hi) + 1))
        else:
            hosts.append(f"{prefix}{part}")
    return hosts


def _assignment_pk(cluster_id: str, partition: str) -> str:
    return f"{cluster_id}${partition}"


def host_assignments(store: StateStore, cluster_id: str,
                     partition: str) -> dict[str, str]:
    """slurm host -> pool node id map."""
    out = {}
    for row in store.query_entities(
            names.TABLE_SLURM,
            partition_key=_assignment_pk(cluster_id, partition)):
        out[row["_rk"]] = row.get("node_id")
    return out


def process_resume(store: StateStore, substrate,
                   pool: PoolSettings, cluster_id: str,
                   partition: str, hosts: list[str],
                   wait_timeout: float = 600.0) -> dict[str, str]:
    """ResumeProgram: grow the pool to cover the requested slurm hosts
    and bind each host to a pool node (process_resume_action :969 +
    wait_for_host_assignment :604 analog)."""
    existing = host_assignments(store, cluster_id, partition)
    needed = [h for h in hosts if h not in existing]
    if not needed:
        return existing
    nodes = pool_mgr.list_nodes(store, pool.id)
    assigned_node_ids = set(existing.values())
    free_nodes = [n for n in nodes
                  if n.state in pool_mgr.READY_STATES and
                  n.node_id not in assigned_node_ids]
    deficit = len(needed) - len(free_nodes)
    if deficit > 0:
        if pool.tpu is not None:
            per_slice = pool.tpu.workers_per_slice
            current_slices = len({n.slice_index for n in nodes})
            add = math.ceil(deficit / per_slice)
            logger.info("slurm resume: growing %s by %d slices",
                        pool.id, add)
            substrate.resize_pool(pool, current_slices + add)
        else:
            substrate.resize_pool(pool, len(nodes) + deficit)
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            nodes = pool_mgr.list_nodes(store, pool.id)
            free_nodes = [
                n for n in nodes
                if n.state in pool_mgr.READY_STATES and
                n.node_id not in assigned_node_ids]
            if len(free_nodes) >= len(needed):
                break
            time.sleep(0.25)
        else:
            raise TimeoutError(
                f"slurm resume: pool {pool.id} did not produce "
                f"{len(needed)} free nodes in {wait_timeout}s")
    pk = _assignment_pk(cluster_id, partition)
    for host, node in zip(needed, free_nodes):
        store.upsert_entity(names.TABLE_SLURM, pk, host, {
            "node_id": node.node_id,
            "internal_ip": node.internal_ip,
            "assigned_at": util.datetime_utcnow_iso(),
        })
        existing[host] = node.node_id
    return existing


def process_suspend(store: StateStore, substrate,
                    pool: PoolSettings, cluster_id: str,
                    partition: str, hosts: list[str]) -> int:
    """SuspendProgram: release host bindings and shrink the pool when
    whole slices become unbound (:1044 analog). Returns releases."""
    pk = _assignment_pk(cluster_id, partition)
    released = 0
    for host in hosts:
        try:
            store.delete_entity(names.TABLE_SLURM, pk, host)
            released += 1
        except NotFoundError:
            continue
    _reclaim_unbound_capacity(store, substrate, pool, cluster_id,
                              partition)
    return released


def _reclaim_unbound_capacity(store: StateStore, substrate,
                              pool: PoolSettings, cluster_id: str,
                              partition: str) -> None:
    bound_nodes = set(host_assignments(store, cluster_id,
                                       partition).values())
    nodes = pool_mgr.list_nodes(store, pool.id)
    if pool.tpu is not None:
        bound_slices = {n.slice_index for n in nodes
                        if n.node_id in bound_nodes}
        all_slices = {n.slice_index for n in nodes}
        target = max(len(bound_slices), 1)
        if len(all_slices) > target:
            logger.info("slurm: reclaiming %s to %d slices",
                        pool.id, target)
            substrate.resize_pool(pool, target)
    else:
        target = max(len(bound_nodes), 1)
        if len(nodes) > target:
            substrate.resize_pool(pool, target)


def idle_reaper(store: StateStore, substrate, pool: PoolSettings,
                cluster_id: str, partition: str,
                idle_reclaim_seconds: float = 900.0,
                now: Optional[float] = None) -> int:
    """Release bindings idle past the reclaim window (daemon_processor
    :1353 analog). Returns released count. 'Idle' = the bound pool
    node is not running tasks and the binding is old enough."""
    now = now if now is not None else time.time()
    pk = _assignment_pk(cluster_id, partition)
    node_state = {n.node_id: n for n in
                  pool_mgr.list_nodes(store, pool.id)}
    released = 0
    for row in list(store.query_entities(names.TABLE_SLURM,
                                         partition_key=pk)):
        node = node_state.get(row.get("node_id"))
        assigned_at = row.get("assigned_at")
        age = now - (util.utcnow().timestamp() if not assigned_at else
                     _parse_iso(assigned_at))
        if node is not None and node.state == "idle" and (
                age > idle_reclaim_seconds):
            store.delete_entity(names.TABLE_SLURM, pk, row["_rk"])
            released += 1
    if released:
        _reclaim_unbound_capacity(store, substrate, pool, cluster_id,
                                  partition)
    return released


def _parse_iso(value: str) -> float:
    import datetime
    return datetime.datetime.fromisoformat(
        value.replace("Z", "+00:00")).timestamp()


def generate_slurm_conf(cluster_id: str, partitions: dict,
                        controller_host: str = "localhost",
                        idle_reclaim_seconds: int = 300,
                        unmanaged_partitions: list = ()) -> str:
    """Generate slurm.conf elastic-partition stanzas with our
    Resume/Suspend programs (reference slurm.conf:101-103 + generated
    wrappers, shipyard_slurm_master_bootstrap.sh:637-668).
    ``idle_reclaim_seconds`` becomes SuspendTime (how long a node
    sits idle before power-save reclaims it — slurm_options.
    idle_reclaim_time_seconds); ``unmanaged_partitions`` are
    passed-through static stanzas for nodes outside the burst
    (reference unmanaged_partitions: each {partition: <line>,
    nodes: [<NodeName lines>]})."""
    lines = [
        f"ClusterName={cluster_id}",
        f"SlurmctldHost={controller_host}",
        "SelectType=select/cons_tres",
        f"SuspendTime={int(idle_reclaim_seconds)}",
        "ResumeTimeout=900",
        "SuspendProgram=/opt/shipyard/slurm_suspend.sh",
        "ResumeProgram=/opt/shipyard/slurm_resume.sh",
        "ResumeFailProgram=/opt/shipyard/slurm_suspend.sh",
        "TreeWidth=65533",
    ]
    for name, part in partitions.items():
        count = int(part.get("max_nodes", 1))
        lines.append(
            f"NodeName={name}-[0-{count - 1}] State=CLOUD "
            f"CPUs={part.get('cpus', 1)}")
        lines.append(
            f"PartitionName={name} Nodes={name}-[0-{count - 1}] "
            f"Default={'YES' if part.get('default') else 'NO'} "
            f"MaxTime=INFINITE State=UP")
    for part in unmanaged_partitions or ():
        for node_line in part.get("nodes", []):
            lines.append(str(node_line))
        if part.get("partition"):
            lines.append(f"PartitionName={part['partition']}")
    return "\n".join(lines) + "\n"

"""Reshard-on-restore: a checkpoint saved at mesh size N restores
onto mesh size M (parallel/sharding.reshard_on_restore + the .MESH
sidecar routing in workloads/checkpoint.restore).

Covers 1->2, 2->4 and 4->2 resizes on the virtual 8-device CPU mesh,
int8-quantized KV-bearing state (dtype preserved bit-for-bit, never
promoted through float), legacy pre-sidecar checkpoint dirs, and the
equivalence oracle: a resume-at-M loss trajectory matches a
fresh-at-M run restored from the same step.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import sharding as shard_rules
from batch_shipyard_tpu.workloads import checkpoint as ckpt_mod


def _mesh(n, tp=1):
    return mesh_mod.make_mesh(
        mesh_mod.auto_axis_sizes(n, tp=tp),
        devices=jax.devices()[:n])


def _state_on(mesh):
    """A small transformer-shaped state: a dp/tp-sharded kernel, an
    int8 KV-style cache leaf with its fp32 scales (the quantized
    serving state shape), and an optax-style opt_state with a scalar
    count."""
    kernel = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)
    kv_int8 = (jnp.arange(4 * 8 * 2 * 4) % 251 - 125).astype(
        jnp.int8).reshape(4, 8, 2, 4)
    scales = jnp.linspace(0.5, 2.0, 4 * 8 * 2).astype(
        jnp.float32).reshape(4, 8, 2)
    params = {
        "proj": {"kernel": jax.device_put(
            kernel, NamedSharding(mesh, P(None, "tp")))},
        "kv_cache": jax.device_put(
            kv_int8, NamedSharding(mesh, P(("dp", "fsdp")))),
        "kv_scales": jax.device_put(
            scales, NamedSharding(mesh, P(("dp", "fsdp")))),
    }
    opt_state = {
        "mu": jax.device_put(kernel * 0.5,
                             NamedSharding(mesh, P(None, "tp"))),
        "count": jax.device_put(jnp.asarray(7, jnp.int32),
                                NamedSharding(mesh, P())),
    }
    return params, opt_state


def _templates_on(mesh, like_params, like_opt):
    def retarget(leaf):
        spec = leaf.sharding.spec
        return jax.device_put(jnp.zeros(leaf.shape, leaf.dtype),
                              NamedSharding(mesh, spec))
    return (jax.tree_util.tree_map(retarget, like_params),
            jax.tree_util.tree_map(retarget, like_opt))


@pytest.mark.parametrize("n_from,n_to", [(1, 2), (2, 4), (4, 2)])
def test_reshard_restore_param_equivalence(tmp_path, n_from, n_to):
    """Values identical across the resize, dtypes preserved (int8
    stays int8), and every restored leaf carries the TARGET mesh's
    sharding."""
    mesh_from = _mesh(n_from)
    params, opt_state = _state_on(mesh_from)
    ckpt_mod.save(str(tmp_path), 5, params, opt_state)
    assert ckpt_mod.saved_mesh_meta(str(tmp_path), 5) is not None

    mesh_to = _mesh(n_to)
    p_tpl, o_tpl = _templates_on(mesh_to, params, opt_state)
    restored = shard_rules.reshard_on_restore(str(tmp_path), p_tpl,
                                              o_tpl)
    assert restored is not None
    r_params, r_opt, step = restored
    assert step == 5
    for got, want in zip(jax.tree_util.tree_leaves(r_params),
                         jax.tree_util.tree_leaves(params)):
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))
    assert r_params["kv_cache"].dtype == jnp.int8
    for leaf, tpl in zip(jax.tree_util.tree_leaves(r_params),
                         jax.tree_util.tree_leaves(p_tpl)):
        assert leaf.sharding == tpl.sharding
    np.testing.assert_array_equal(np.asarray(r_opt["count"]), 7)


def test_restore_routes_resize_through_reshard(tmp_path):
    """checkpoint.restore detects the mesh change via the .MESH
    sidecar and routes through the reshard path (no exception-driven
    fallback needed)."""
    mesh2 = _mesh(2)
    params, opt_state = _state_on(mesh2)
    ckpt_mod.save(str(tmp_path), 3, params, opt_state)
    mesh4 = _mesh(4)
    p_tpl, o_tpl = _templates_on(mesh4, params, opt_state)
    r_params, _r_opt, step = ckpt_mod.restore(str(tmp_path), p_tpl,
                                              o_tpl)
    assert step == 3
    np.testing.assert_array_equal(
        np.asarray(r_params["proj"]["kernel"]),
        np.asarray(params["proj"]["kernel"]))
    assert r_params["proj"]["kernel"].sharding == \
        p_tpl["proj"]["kernel"].sharding


def test_legacy_dir_without_sidecar_still_restores(tmp_path):
    """Pre-sidecar checkpoint dirs (the fleet's existing resume
    points): no .MESH file -> the strict path restores at the same
    mesh unchanged, and reshard_on_restore works on them too."""
    mesh2 = _mesh(2)
    params, opt_state = _state_on(mesh2)
    ckpt_mod.save(str(tmp_path), 9, params, opt_state)
    os.remove(ckpt_mod._mesh_meta_path(str(tmp_path), 9))
    assert ckpt_mod.saved_mesh_meta(str(tmp_path), 9) is None
    # Same mesh, strict path.
    p_tpl, o_tpl = _templates_on(mesh2, params, opt_state)
    r_params, _r_opt, step = ckpt_mod.restore(str(tmp_path), p_tpl,
                                              o_tpl)
    assert step == 9
    np.testing.assert_array_equal(np.asarray(r_params["kv_cache"]),
                                  np.asarray(params["kv_cache"]))
    # Legacy dir onto a NEW mesh via the explicit reshard path.
    mesh4 = _mesh(4)
    p_tpl4, o_tpl4 = _templates_on(mesh4, params, opt_state)
    r4 = shard_rules.reshard_on_restore(str(tmp_path), p_tpl4,
                                        o_tpl4)
    assert r4 is not None
    np.testing.assert_array_equal(np.asarray(r4[0]["kv_cache"]),
                                  np.asarray(params["kv_cache"]))


def test_reshard_rejects_wrong_model_shape(tmp_path):
    """Global shapes are mesh-independent: a shape mismatch means a
    DIFFERENT model config, and reshard must refuse loudly instead of
    silently truncating."""
    mesh2 = _mesh(2)
    params, opt_state = _state_on(mesh2)
    ckpt_mod.save(str(tmp_path), 1, params, opt_state)
    bad = {
        **params,
        "proj": {"kernel": jax.device_put(
            jnp.zeros((4, 16), jnp.float32),
            NamedSharding(mesh2, P(None, "tp")))},
    }
    with pytest.raises(Exception):
        shard_rules.reshard_on_restore(str(tmp_path), bad, opt_state)


def test_retention_gc_removes_mesh_sidecar(tmp_path):
    mesh1 = _mesh(1)
    params, opt_state = _state_on(mesh1)
    ckpt_mod.save(str(tmp_path), 1, params, opt_state)
    ckpt_mod.save(str(tmp_path), 2, params, opt_state)
    removed = ckpt_mod.retention_gc(str(tmp_path), keep_last=1)
    assert removed == [1]
    assert not os.path.exists(
        ckpt_mod._mesh_meta_path(str(tmp_path), 1))
    assert ckpt_mod.saved_mesh_meta(str(tmp_path), 2) is not None


@pytest.mark.slow
def test_loss_trajectory_equivalence_oracle(tmp_path):
    """THE acceptance oracle: train at mesh size 2, checkpoint, then
    (a) resume-at-4 through checkpoint.restore (sidecar-routed
    reshard) and (b) fresh-at-4 via reshard_on_restore from the same
    step — the two loss trajectories match to fp tolerance.

    Marked slow: the three extra harness compiles this late in a
    full-suite run reproducibly segfault XLA CPU on the 1-core test
    container (accumulated-compile state; the test passes standalone
    and in any partial-suite combination). The array-level
    equivalence tests above exercise the identical restore mechanism
    in tier-1; this oracle additionally proves the post-restore STEP
    trajectories agree."""
    from batch_shipyard_tpu.parallel import train as train_mod

    def harness_for(n, tp=1):
        mesh = _mesh(n, tp=tp)
        config = train_mod.make_transformer_config(
            mesh, vocab_size=64, d_model=16, n_layers=1, n_heads=2,
            d_head=8, d_ff=32, max_seq_len=32)
        return train_mod.build_transformer_train(
            mesh, config, batch_size=4, seq_len=8)

    def batch_for(harness, seed):
        rng = np.random.RandomState(seed)
        tokens = rng.randint(0, 64, (4, 8)).astype(np.int32)
        return {
            "tokens": jax.device_put(jnp.asarray(tokens),
                                     harness.batch_sharding),
            "targets": jax.device_put(jnp.asarray(tokens),
                                      harness.batch_sharding)}

    h2 = harness_for(2)
    params, opt_state = h2.params, h2.opt_state
    for i in range(2):
        params, opt_state, _ = h2.step(params, opt_state,
                                       batch_for(h2, i))
    ckpt_mod.save(str(tmp_path), 2, params, opt_state)

    h4 = harness_for(4, tp=2)
    resumed = ckpt_mod.restore(str(tmp_path), h4.params,
                               h4.opt_state)
    assert resumed is not None and resumed[2] == 2
    p_a, o_a = resumed[0], resumed[1]
    losses_resumed = []
    for i in range(2, 5):
        p_a, o_a, metrics = h4.step(p_a, o_a, batch_for(h4, i))
        losses_resumed.append(float(metrics["loss"]))

    h4b = harness_for(4, tp=2)
    fresh = shard_rules.reshard_on_restore(str(tmp_path), h4b.params,
                                           h4b.opt_state)
    p_b, o_b = fresh[0], fresh[1]
    losses_fresh = []
    for i in range(2, 5):
        p_b, o_b, metrics = h4b.step(p_b, o_b, batch_for(h4b, i))
        losses_fresh.append(float(metrics["loss"]))
    np.testing.assert_allclose(losses_resumed, losses_fresh,
                               rtol=1e-5, atol=1e-6)

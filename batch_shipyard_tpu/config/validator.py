"""Strict schema validation for user-facing YAML configs.

The reference delegates this to pykwalify (convoy/validator.py:112 +
schemas/*.yaml, strict_rule_validation) and treats schema validation as
the de-facto type system of the product (SURVEY.md section 4). pykwalify
is not available here, so this module implements a small, strict,
self-contained schema engine with the subset of semantics we need:

  - ``type``: map | seq | str | int | float | number | bool | any
  - map: ``mapping`` of key -> schema; unknown keys are errors unless
    ``allow_unknown: true``; per-key ``required: true``
  - seq: ``sequence`` holding the element schema
  - scalars: ``enum``, ``pattern`` (anchored regex), ``range`` {min,max}
  - ``nullable: true`` permits explicit nulls

Schemas live in batch_shipyard_tpu/config/schemas/<config_type>.yaml.
"""

from __future__ import annotations

import enum
import functools
import pathlib
import re
from typing import Any

import yaml

_SCHEMA_DIR = pathlib.Path(__file__).parent / "schemas"


class ConfigType(enum.Enum):
    """The user-facing config file types (reference: validator.py:54)."""

    CREDENTIALS = "credentials"
    GLOBAL = "config"
    POOL = "pool"
    JOBS = "jobs"
    REMOTEFS = "fs"
    MONITOR = "monitor"
    FEDERATION = "federation"
    SLURM = "slurm"


class ValidationError(ValueError):
    """Raised when a config fails schema validation."""

    def __init__(self, config_type: str, errors: list[str]):
        self.config_type = config_type
        self.errors = errors
        msg = "{} config failed validation:\n  {}".format(
            config_type, "\n  ".join(errors))
        super().__init__(msg)


_SCALAR_TYPES: dict[str, tuple[type, ...]] = {
    "str": (str,),
    "int": (int,),
    "float": (float, int),
    "number": (int, float),
    "bool": (bool,),
}


def _check_scalar(value: Any, schema: dict, path: str,
                  errors: list[str]) -> None:
    stype = schema.get("type", "any")
    if stype != "any":
        expected = _SCALAR_TYPES[stype]
        # bool is a subclass of int in Python; reject bools for numerics.
        if isinstance(value, bool) and stype != "bool":
            errors.append(f"{path}: expected {stype}, got bool")
            return
        if not isinstance(value, expected):
            errors.append(
                f"{path}: expected {stype}, got {type(value).__name__}")
            return
    if "enum" in schema and value not in schema["enum"]:
        errors.append(
            "{}: value {!r} not one of {}".format(path, value, schema["enum"]))
    if "pattern" in schema:
        if not isinstance(value, str) or not re.fullmatch(
                schema["pattern"], value):
            errors.append(
                "{}: value {!r} does not match pattern {!r}".format(
                    path, value, schema["pattern"]))
    if "range" in schema and isinstance(value, (int, float)) and not (
            isinstance(value, bool)):
        rng = schema["range"]
        if "min" in rng and value < rng["min"]:
            errors.append(f"{path}: value {value} < min {rng['min']}")
        if "max" in rng and value > rng["max"]:
            errors.append(f"{path}: value {value} > max {rng['max']}")


def _validate_node(value: Any, schema: dict, path: str,
                   errors: list[str]) -> None:
    if value is None:
        if schema.get("nullable", False):
            return
        errors.append(f"{path}: null is not allowed")
        return
    stype = schema.get("type", "any")
    if stype == "map":
        if not isinstance(value, dict):
            errors.append(f"{path}: expected map, got {type(value).__name__}")
            return
        mapping = schema.get("mapping", {})
        if not schema.get("allow_unknown", False):
            for key in value:
                if key not in mapping:
                    errors.append(f"{path}.{key}: unknown key")
        for key, sub in mapping.items():
            if key in value:
                _validate_node(value[key], sub, f"{path}.{key}", errors)
            elif sub.get("required", False):
                errors.append(f"{path}.{key}: required key missing")
    elif stype == "seq":
        if not isinstance(value, list):
            errors.append(f"{path}: expected seq, got {type(value).__name__}")
            return
        elem = schema.get("sequence")
        if elem is not None:
            for idx, item in enumerate(value):
                _validate_node(item, elem, f"{path}[{idx}]", errors)
        if "range" in schema:
            rng = schema["range"]
            if "min" in rng and len(value) < rng["min"]:
                errors.append(
                    f"{path}: sequence shorter than min {rng['min']}")
            if "max" in rng and len(value) > rng["max"]:
                errors.append(f"{path}: sequence longer than max {rng['max']}")
    else:
        _check_scalar(value, schema, path, errors)


@functools.lru_cache(maxsize=None)
def _load_schema(config_type: str) -> dict:
    schema_file = _SCHEMA_DIR / f"{config_type}.yaml"
    if not schema_file.exists():
        raise FileNotFoundError(f"no schema for config type {config_type}")
    with open(schema_file, "r", encoding="utf-8") as fh:
        return yaml.safe_load(fh)


def validate(data: Any, schema: dict, root: str = "$") -> list[str]:
    """Validate data against an inline schema; return error list."""
    errors: list[str] = []
    _validate_node(data, schema, root, errors)
    return errors


def validate_config(config_type: ConfigType | str, data: Any,
                    raise_on_error: bool = True) -> list[str]:
    """Validate a config dict against its file-type schema."""
    name = (config_type.value if isinstance(config_type, ConfigType)
            else config_type)
    schema = _load_schema(name)
    errors = validate(data, schema, root=name)
    if errors and raise_on_error:
        raise ValidationError(name, errors)
    return errors

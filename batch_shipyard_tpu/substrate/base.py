"""Compute substrate interface: where nodes come from.

The reference's L0 is Azure Batch pool allocation (create_pool
batch.py:921 -> service allocates VMs -> start task runs nodeprep). Our
substrates allocate TPU pod slices (gcp_tpu), simulate them in-process
(fake — the test substrate SURVEY.md section 4 calls for), or run agents
as local processes (localhost — used to drive the attached real TPU
chip end-to-end).

Pool semantics note (SURVEY.md section 7 hard parts): a TPU pod slice is
allocated atomically with N workers — 'resize' means adding/removing
whole slices and 'reboot one node' means recreating a slice. The
substrate interface therefore exposes slice-granular operations; the
pool manager maps node-granular recovery requests onto them.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Optional

from batch_shipyard_tpu.config.settings import (
    CredentialsSettings, PoolSettings)
from batch_shipyard_tpu.state.base import StateStore


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    node_id: str
    state: str
    hostname: str
    internal_ip: str
    node_index: int
    slice_index: int
    worker_index: int
    # Self-healing surface: the agent's health score in [0, 1] and
    # whether the node quarantined itself (auto-drain; excluded from
    # claims and gang formation).
    health: float = 1.0
    quarantined: bool = False


class ComputeSubstrate(abc.ABC):
    """Allocates and manages the machines of one or more pools."""

    @abc.abstractmethod
    def allocate_pool(self, pool: PoolSettings) -> None:
        """Begin allocation of all slices/nodes; returns immediately.
        Node state convergence is observed via TABLE_NODES."""

    @abc.abstractmethod
    def deallocate_pool(self, pool_id: str) -> None: ...

    @abc.abstractmethod
    def resize_pool(self, pool: PoolSettings, num_slices: int) -> None:
        """Grow/shrink to num_slices slices (TPU) or num nodes
        (VM pools)."""

    @abc.abstractmethod
    def recreate_slice(self, pool: PoolSettings, slice_index: int) -> None:
        """Tear down and re-allocate one slice ('reboot' analog)."""

    def deallocate_slice(self, pool: PoolSettings,
                         slice_index: int) -> None:
        """Tear down one slice WITHOUT replacement ('pool nodes del'
        analog — TPU removal granularity is the slice; the pool
        shrinks until a resize grows it back)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support slice "
            f"deallocation")

    @abc.abstractmethod
    def get_remote_login(self, pool_id: str,
                         node_id: str) -> Optional[tuple[str, int]]:
        """(ip, ssh port) for a node, if reachable."""

    def suspend_pool(self, pool: PoolSettings) -> None:
        """Stop the pool's machines without losing its definition
        (suspend/start parity: reference fleet.py:3203+ for fs/monitor/
        fed/slurm resources; TPU VMs support stop/start)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support suspend")

    def start_pool(self, pool: PoolSettings) -> None:
        """Restart a suspended pool's machines."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support start")

    def ensure_attached(self, pool: PoolSettings) -> None:
        """Re-attach to an existing pool from a fresh process.

        Real substrates are no-ops (nodes are independent machines);
        the in-process fake substrate revives its simulated agents so
        CLI invocations in separate processes keep working.
        """


def create_substrate(kind: str, store: StateStore,
                     credentials: CredentialsSettings,
                     **kwargs) -> ComputeSubstrate:
    if kind == "fake":
        from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
        return FakePodSubstrate(store, **kwargs)
    if kind == "localhost":
        from batch_shipyard_tpu.substrate.localhost import (
            LocalhostSubstrate)
        return LocalhostSubstrate(store, credentials, **kwargs)
    if kind == "tpu_vm":
        from batch_shipyard_tpu.substrate.gcp_tpu import GcpTpuSubstrate
        return GcpTpuSubstrate(store, credentials, **kwargs)
    raise ValueError(f"unknown substrate {kind!r}")

"""In-memory state store: the unit-test fake (thread-safe).

Shares exact semantics with the GCS/localfs stores so distributed
protocols (cascade lease gate, federation queues, slurm handshake) can
run multi-threaded in one process under test.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.state import base
from batch_shipyard_tpu.state.base import (
    EntityExistsError, EtagMismatchError, LeaseHandle, LeaseLostError,
    NotFoundError, ObjectMeta, PreconditionFailedError, QueueMessage)
from batch_shipyard_tpu.utils import util


class MemoryStateStore(base.StateStore):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        # key -> (bytes, generation, updated)
        self._objects: dict[str, tuple[bytes, int, Any]] = {}
        self._generation = 0
        # lease key -> (owner, token, expires_at)
        self._leases: dict[str, tuple[str, str, float]] = {}
        # table -> {(pk, rk) -> (entity, etag)}
        self._tables: dict[str, dict[tuple[str, str], tuple[dict, str]]] = {}
        # queue -> list of [message_id, payload, visible_at, dequeue_count]
        self._queues: dict[str, list[list]] = {}
        # claimed messages: (queue, message_id) -> pop_receipt
        self._claims: dict[tuple[str, str], str] = {}

    # ------------------------------ objects ----------------------------

    def put_object(self, key: str, data: bytes,
                   if_generation_match: Optional[int] = None) -> int:
        with self._lock:
            current = self._objects.get(key)
            if if_generation_match is not None:
                cur_gen = current[1] if current is not None else 0
                if cur_gen != if_generation_match:
                    raise PreconditionFailedError(
                        f"{key}: generation {cur_gen} != "
                        f"{if_generation_match}")
            self._generation += 1
            self._objects[key] = (bytes(data), self._generation,
                                  util.utcnow())
            return self._generation

    def get_object(self, key: str) -> bytes:
        with self._lock:
            if key not in self._objects:
                raise NotFoundError(key)
            return self._objects[key][0]

    def get_object_meta(self, key: str) -> ObjectMeta:
        with self._lock:
            if key not in self._objects:
                raise NotFoundError(key)
            data, gen, updated = self._objects[key]
            return ObjectMeta(key=key, size=len(data), generation=gen,
                              updated=updated)

    def delete_object(self, key: str,
                      if_generation_match: Optional[int] = None) -> None:
        with self._lock:
            if key not in self._objects:
                raise NotFoundError(key)
            if if_generation_match is not None and (
                    self._objects[key][1] != if_generation_match):
                raise PreconditionFailedError(key)
            del self._objects[key]

    def list_objects(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._objects if k.startswith(prefix))

    # ------------------------------ leases -----------------------------

    def acquire_lease(self, key: str, duration_seconds: float,
                      owner: str) -> Optional[LeaseHandle]:
        now = time.monotonic()
        with self._lock:
            held = self._leases.get(key)
            if held is not None and held[2] > now:
                return None
            token = uuid.uuid4().hex
            expires = now + duration_seconds
            self._leases[key] = (owner, token, expires)
            return LeaseHandle(key=key, owner=owner, token=token,
                               expires_at=expires)

    def renew_lease(self, handle: LeaseHandle,
                    duration_seconds: float) -> LeaseHandle:
        now = time.monotonic()
        with self._lock:
            held = self._leases.get(handle.key)
            if held is None or held[1] != handle.token or held[2] <= now:
                raise LeaseLostError(handle.key)
            expires = now + duration_seconds
            self._leases[handle.key] = (handle.owner, handle.token, expires)
            return LeaseHandle(key=handle.key, owner=handle.owner,
                               token=handle.token, expires_at=expires)

    def release_lease(self, handle: LeaseHandle) -> None:
        with self._lock:
            held = self._leases.get(handle.key)
            if held is None or held[1] != handle.token:
                raise LeaseLostError(handle.key)
            del self._leases[handle.key]

    # ------------------------------ tables -----------------------------

    def _table(self, table: str) -> dict:
        return self._tables.setdefault(table, {})

    def insert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        with self._lock:
            tbl = self._table(table)
            if (partition_key, row_key) in tbl:
                raise EntityExistsError(f"{table}:{partition_key}:{row_key}")
            etag = uuid.uuid4().hex
            tbl[(partition_key, row_key)] = (dict(entity), etag)
            return etag

    def insert_entities(self, table: str,
                        rows: list[tuple[str, str, dict]]) -> list[str]:
        """One lock acquisition for the whole batch, validated before
        any write lands — a batch either inserts whole or not at all
        (strictly stronger than the base contract's abort-at-failing-
        row, and what the group-commit torn-batch drill pins)."""
        with self._lock:
            tbl = self._table(table)
            for pk, rk, _entity in rows:
                if (pk, rk) in tbl:
                    raise EntityExistsError(f"{table}:{pk}:{rk}")
            etags = []
            for pk, rk, entity in rows:
                etag = uuid.uuid4().hex
                tbl[(pk, rk)] = (dict(entity), etag)
                etags.append(etag)
            return etags

    def count_entities_by(self, table: str, partition_key: str,
                          column: str = "state") -> dict[str, int]:
        """Count under the lock without materializing per-row copies
        (the query_entities fallback builds three-key-decorated dicts
        per row — pure waste when the caller only wants a tally)."""
        counts: dict[str, int] = {}
        with self._lock:
            for (pk, _rk), (entity, _etag) in \
                    self._table(table).items():
                if pk != partition_key:
                    continue
                value = str(entity.get(column) or "")
                counts[value] = counts.get(value, 0) + 1
        return counts

    def upsert_entity(self, table: str, partition_key: str, row_key: str,
                      entity: dict[str, Any]) -> str:
        with self._lock:
            etag = uuid.uuid4().hex
            self._table(table)[(partition_key, row_key)] = (
                dict(entity), etag)
            return etag

    def merge_entity(self, table: str, partition_key: str, row_key: str,
                     entity: dict[str, Any],
                     if_match: Optional[str] = None) -> str:
        with self._lock:
            tbl = self._table(table)
            if (partition_key, row_key) not in tbl:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            current, etag = tbl[(partition_key, row_key)]
            if if_match is not None and if_match != etag:
                raise EtagMismatchError(
                    f"{table}:{partition_key}:{row_key}")
            merged = dict(current)
            merged.update(entity)
            new_etag = uuid.uuid4().hex
            tbl[(partition_key, row_key)] = (merged, new_etag)
            return new_etag

    def get_entity(self, table: str, partition_key: str,
                   row_key: str) -> dict[str, Any]:
        with self._lock:
            tbl = self._table(table)
            if (partition_key, row_key) not in tbl:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            entity, etag = tbl[(partition_key, row_key)]
            out = dict(entity)
            out["_etag"] = etag
            out["_pk"] = partition_key
            out["_rk"] = row_key
            return out

    def query_entities(self, table: str,
                       partition_key: Optional[str] = None,
                       row_key_prefix: str = "",
                       ) -> Iterator[dict[str, Any]]:
        with self._lock:
            items = sorted(self._table(table).items())
        for (pk, rk), (entity, etag) in items:
            if partition_key is not None and pk != partition_key:
                continue
            if row_key_prefix and not rk.startswith(row_key_prefix):
                continue
            out = dict(entity)
            out["_etag"] = etag
            out["_pk"] = pk
            out["_rk"] = rk
            yield out

    def delete_entity(self, table: str, partition_key: str, row_key: str,
                      if_match: Optional[str] = None) -> None:
        with self._lock:
            tbl = self._table(table)
            if (partition_key, row_key) not in tbl:
                raise NotFoundError(f"{table}:{partition_key}:{row_key}")
            if if_match is not None and tbl[
                    (partition_key, row_key)][1] != if_match:
                raise EtagMismatchError(f"{table}:{partition_key}:{row_key}")
            del tbl[(partition_key, row_key)]

    # ------------------------------ queues -----------------------------

    def put_message(self, queue: str, payload: bytes,
                    delay_seconds: float = 0.0) -> str:
        with self._lock:
            message_id = uuid.uuid4().hex
            self._queues.setdefault(queue, []).append(
                [message_id, bytes(payload),
                 time.monotonic() + delay_seconds, 0])
            return message_id

    def put_messages(self, queue: str, payloads: list[bytes],
                     delay_seconds: float = 0.0) -> list[str]:
        """One lock acquisition per batch (the localfs override's
        single-fsync rationale, minus the fsync)."""
        with self._lock:
            q = self._queues.setdefault(queue, [])
            visible = time.monotonic() + delay_seconds
            ids = []
            for payload in payloads:
                message_id = uuid.uuid4().hex
                q.append([message_id, bytes(payload), visible, 0])
                ids.append(message_id)
            return ids

    def get_messages(self, queue: str, max_messages: int = 1,
                     visibility_timeout: float = 30.0,
                     ) -> list[QueueMessage]:
        now = time.monotonic()
        out: list[QueueMessage] = []
        with self._lock:
            for msg in self._queues.get(queue, []):
                if len(out) >= max_messages:
                    break
                if msg[2] > now:
                    continue
                msg[2] = now + visibility_timeout
                msg[3] += 1
                receipt = uuid.uuid4().hex
                self._claims[(queue, msg[0])] = receipt
                out.append(QueueMessage(
                    queue=queue, message_id=msg[0], pop_receipt=receipt,
                    payload=msg[1], dequeue_count=msg[3]))
        return out

    def _find_message(self, message: QueueMessage) -> list:
        for msg in self._queues.get(message.queue, []):
            if msg[0] == message.message_id:
                return msg
        raise NotFoundError(message.message_id)

    def delete_message(self, message: QueueMessage) -> None:
        with self._lock:
            if self._claims.get(
                    (message.queue, message.message_id)
                    ) != message.pop_receipt:
                raise NotFoundError(message.message_id)
            msg = self._find_message(message)
            self._queues[message.queue].remove(msg)
            del self._claims[(message.queue, message.message_id)]

    def update_message(self, message: QueueMessage,
                       visibility_timeout: float) -> QueueMessage:
        with self._lock:
            if self._claims.get(
                    (message.queue, message.message_id)
                    ) != message.pop_receipt:
                raise NotFoundError(message.message_id)
            msg = self._find_message(message)
            msg[2] = time.monotonic() + visibility_timeout
            return message

    def queue_length(self, queue: str) -> int:
        with self._lock:
            return len(self._queues.get(queue, []))

    def clear(self) -> None:
        with self._lock:
            self._objects.clear()
            self._leases.clear()
            self._tables.clear()
            self._queues.clear()
            self._claims.clear()

"""Control-plane partition tolerance (ISSUE 13).

Three layers under test:

  * lease-based sweep leadership with monotonic fencing epochs
    (state/leases.py) — acquisition exclusivity, partition
    abdication on the local clock, epoch monotonicity, fencing;
  * store-outage ride-through (state/resilient.py) — critical-op
    retry, advisory WAL ordering/coalescing, replay idempotence,
    crash-restart backlog drain, the store_outage pricing event;
  * agent crash-restart adoption (slot ledger + watcher) — the
    exited-while-unowned classification path, plus the three seeded
    chaos drills that pin the whole stack end to end.
"""

import json
import os
import threading
import time

import pytest

from batch_shipyard_tpu.state import leases as state_leases
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import NotFoundError
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.state.resilient import ResilientStore

LEASE_KEY = "leader/testpool/role"
EPOCH_KEY = "leader/testpool/role.epoch"


def _lease(store, owner, duration=0.6, blocked=None):
    return state_leases.LeaderLease(
        store, LEASE_KEY, EPOCH_KEY, owner,
        duration_seconds=duration, blocked=blocked)


# ------------------------------- leases --------------------------------

def test_lease_exclusive_and_epoch_monotonic():
    store = MemoryStateStore()
    a, b = _lease(store, "A"), _lease(store, "B")
    e1 = a.epoch()
    assert e1 is not None
    # Held: the second owner cannot acquire, and re-entry by the
    # holder stays in the SAME term (no epoch churn).
    assert b.epoch() is None
    assert a.epoch() == e1
    assert a.fenced(e1)
    info = state_leases.read_leader(store, EPOCH_KEY)
    assert info["owner"] == "A" and info["epoch"] == e1
    # Graceful release: the successor acquires immediately, in a NEW
    # strictly-later term.
    a.release()
    e2 = b.epoch()
    assert e2 is not None and e2 > e1
    assert not a.fenced(e1)


def test_lease_partition_abdicates_before_successor():
    """THE double-leader window test: a holder partitioned from the
    store loses local authority (fenced() false, epoch() None)
    strictly before the successor can acquire — at no instant do two
    owners both believe they lead."""
    store = MemoryStateStore()
    blocked = [False]
    a = _lease(store, "A", duration=0.5,
               blocked=lambda: blocked[0])
    b = _lease(store, "B", duration=0.5)
    e1 = a.epoch()
    assert e1 is not None
    blocked[0] = True
    # Poll both sides through the handover: record any instant where
    # both claim authority.
    overlap = False
    b_epoch = None
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        a_has = a.fenced(e1)
        b_epoch = b.epoch()
        if a_has and b_epoch is not None:
            overlap = True
        if b_epoch is not None:
            break
        time.sleep(0.02)
    assert b_epoch is not None, "successor never acquired"
    assert not overlap, "double leader: both held authority at once"
    assert b_epoch > e1
    # The deposed holder knows it on its own clock, store unreachable.
    assert a.epoch() is None


def test_lease_epoch_bump_failure_abdicates():
    """A leader that cannot record its fencing epoch must not act:
    the acquisition is rolled back (lease released) so a functional
    peer can lead instead."""
    store = MemoryStateStore()

    class NoEpochStore:
        def __getattr__(self, name):
            attr = getattr(store, name)
            if name == "put_object":
                def broken(*a, **k):
                    raise RuntimeError("epoch object unwritable")
                return broken
            return attr

    a = state_leases.LeaderLease(NoEpochStore(), LEASE_KEY,
                                 EPOCH_KEY, "A",
                                 duration_seconds=0.5)
    assert a.epoch() is None
    b = _lease(store, "B", duration=0.5)
    assert b.epoch() is not None


# --------------------------- resilient store ---------------------------

class FlakyStore:
    """Transport-failure wrapper: every op raises while .down."""

    def __init__(self, inner):
        self.inner = inner
        self.down = False
        self.calls = []

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if not callable(attr):
            return attr

        def call(*args, **kwargs):
            self.calls.append(name)
            if self.down:
                raise RuntimeError("store down")
            return attr(*args, **kwargs)
        return call


def _resilient(flaky, tmp_path, **kw):
    kw.setdefault("retry_base", 0.02)
    kw.setdefault("retry_cap", 0.1)
    kw.setdefault("probe_interval", 0.05)
    return ResilientStore(flaky, str(tmp_path / "wal.jsonl"),
                          pool_id="testpool", node_id="n0", **kw)


def test_resilient_critical_retries_and_prices_outage(tmp_path):
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path)
    flaky.down = True
    threading.Timer(0.25, lambda: setattr(flaky, "down",
                                          False)).start()
    t0 = time.monotonic()
    rs.insert_entity(names.TABLE_TASKS, "p$j", "t0",
                     {"state": "pending"})
    assert time.monotonic() - t0 >= 0.2
    # The op landed exactly once and the outage window was priced
    # with the exact [first-failure, first-success] interval.
    assert raw.get_entity(names.TABLE_TASKS, "p$j",
                          "t0")["state"] == "pending"
    outages = [r for r in raw.query_entities(names.TABLE_GOODPUT)
               if r["kind"] == "store_outage"]
    assert len(outages) == 1
    assert outages[0]["end"] - outages[0]["start"] >= 0.2
    assert outages[0]["node_id"] == "n0"


def test_resilient_put_stream_rides_outage_untorn(tmp_path):
    """put_object_stream is critical (output uploads are what the
    completion path's classification hangs on) AND retry-safe: the
    single-shot chunk iterator is spooled locally once, so a retry
    after a failed attempt re-streams the WHOLE payload — never a
    torn object from a half-consumed iterator."""
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path)
    payload = [b"aa", b"bb", b"cc"]
    consumed = []

    def chunks():
        for block in payload:
            consumed.append(block)
            yield block

    flaky.down = True
    threading.Timer(0.25, lambda: setattr(flaky, "down",
                                          False)).start()
    rs.put_object_stream("outputs/k", chunks())
    assert raw.get_object("outputs/k") == b"aabbcc"
    # The caller's iterator was consumed exactly once, up front.
    assert consumed == payload
    # And the ride-through was priced like any critical op's.
    outages = [r for r in raw.query_entities(names.TABLE_GOODPUT)
               if r["kind"] == "store_outage"]
    assert len(outages) == 1


def test_resilient_get_stream_retries_open(tmp_path):
    """get_object_stream retries open + first chunk through an
    outage (backends implement it as a generator, so the bare call
    never fails); a missing key still surfaces as NotFoundError at
    the call."""
    raw = MemoryStateStore()
    raw.put_object("k", b"x" * 100)
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path)
    flaky.down = True
    threading.Timer(0.2, lambda: setattr(flaky, "down",
                                         False)).start()
    assert b"".join(rs.get_object_stream("k")) == b"x" * 100
    with pytest.raises(NotFoundError):
        list(rs.get_object_stream("missing"))


def test_resilient_critical_ceiling_survives_latch_flap(tmp_path):
    """The retry ceiling is per-CALL, not per-latch: a deterministic
    caller error failing against a healthy store keeps re-latching
    an 'outage' that concurrent advisory probes immediately clear —
    a latch-based clock would restart from ~0 every attempt and
    retry forever. The call must hit StoreOutageError at the
    ceiling regardless of the flapping."""
    from batch_shipyard_tpu.state.resilient import StoreOutageError

    raw = MemoryStateStore()

    class OneOpBroken:
        def __init__(self, inner):
            self.inner = inner

        def __getattr__(self, name):
            attr = getattr(self.inner, name)
            if name == "merge_entity":
                def broken(*a, **k):
                    raise RuntimeError("caller bug")
                return broken
            return attr

    rs = ResilientStore(OneOpBroken(raw),
                        str(tmp_path / "wal.jsonl"),
                        pool_id="testpool", node_id="n0",
                        retry_base=0.02, retry_cap=0.05,
                        probe_interval=0.01,
                        max_outage_seconds=0.4)
    stop = threading.Event()

    def flapper():
        while not stop.is_set():
            # Healthy advisory traffic: journals under the latch,
            # probes, recovers — flapping the latch open.
            rs.insert_entity(names.TABLE_GOODPUT, "testpool",
                             f"f{time.monotonic()}", {"kind": "idle",
                                                      "start": 0,
                                                      "end": 1})
            time.sleep(0.02)

    thread = threading.Thread(target=flapper, daemon=True)
    thread.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(StoreOutageError):
            rs.merge_entity(names.TABLE_TASKS, "p$j", "t",
                            {"state": "x"})
        assert time.monotonic() - t0 < 5.0
    finally:
        stop.set()
        thread.join(timeout=5.0)


def test_resilient_semantic_errors_propagate(tmp_path):
    rs = _resilient(FlakyStore(MemoryStateStore()), tmp_path)
    with pytest.raises(NotFoundError):
        rs.get_entity(names.TABLE_TASKS, "p$j", "missing")
    # No outage was latched by a successful round trip.
    assert rs.journal_backlog() == 0


def test_resilient_advisory_wal_order_and_replay(tmp_path):
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    flaky.down = True
    for i in range(4):
        rs.insert_entity(names.TABLE_GOODPUT, "testpool",
                         f"{i:03d}$r", {"kind": "idle", "seq": i,
                                        "start": i, "end": i + 1})
    assert rs.journal_backlog() == 4
    assert os.path.exists(tmp_path / "wal.jsonl")
    # Recovery through a critical op replays IN ORDER.
    flaky.down = False
    rs.queue_length("q")
    assert rs.journal_backlog() == 0
    rows = sorted(raw.query_entities(names.TABLE_GOODPUT),
                  key=lambda r: r["_rk"])
    seqs = [r["seq"] for r in rows if r["kind"] == "idle"]
    assert seqs == [0, 1, 2, 3]
    assert not os.path.exists(tmp_path / "wal.jsonl")


def test_resilient_heartbeat_coalescing(tmp_path):
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    raw.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                      {"state": "idle"})
    flaky.down = True
    for beat in range(10):
        rs.merge_entity(names.TABLE_NODES, "testpool", "n0",
                        {"heartbeat_at": float(beat),
                         "running_tasks": beat % 3})
    # O(entities), not O(outage duration) — and the merged payload
    # is the NEWEST.
    assert rs.journal_backlog() == 1
    flaky.down = False
    rs.queue_length("q")
    node = raw.get_entity(names.TABLE_NODES, "testpool", "n0")
    assert node["heartbeat_at"] == 9.0
    assert node["state"] == "idle"


def test_resilient_coalescing_respects_op_boundaries(tmp_path):
    """Coalescing folds repeats into the NEWEST same-op entry only
    (review fix): an upsert journaled between two merges is a full-
    row replace — folding the later merge backwards across it (or
    replaying the upsert with merge semantics) would resurrect
    columns the upsert dropped."""
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    raw.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                      {"state": "idle", "extra": "stale"})
    flaky.down = True
    rs.merge_entity(names.TABLE_NODES, "testpool", "n0",
                    {"heartbeat_at": 1.0})
    rs.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                     {"state": "running"})
    rs.merge_entity(names.TABLE_NODES, "testpool", "n0",
                    {"heartbeat_at": 2.0})
    # Three entries: the trailing merge must not cross the upsert.
    assert rs.journal_backlog() == 3
    flaky.down = False
    rs.queue_length("q")
    assert rs.journal_backlog() == 0
    node = raw.get_entity(names.TABLE_NODES, "testpool", "n0")
    assert node["state"] == "running"
    assert node["heartbeat_at"] == 2.0
    # The upsert's replace semantics survived the journal.
    assert "extra" not in node


def test_resilient_replay_idempotent_after_crash(tmp_path):
    """Crash-mid-replay: entries already applied re-insert into
    EntityExistsError, which replay treats as success — no
    double-counted intervals."""
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    flaky.down = True
    rs.insert_entity(names.TABLE_GOODPUT, "testpool", "000$r",
                     {"kind": "idle", "start": 0, "end": 1})
    rs.insert_entity(names.TABLE_GOODPUT, "testpool", "001$r",
                     {"kind": "idle", "start": 1, "end": 2})
    # Simulate the crash: the first entry was ALREADY applied before
    # the journal could be trimmed.
    raw.insert_entity(names.TABLE_GOODPUT, "testpool", "000$r",
                      {"kind": "idle", "start": 0, "end": 1})
    flaky.down = False
    # A fresh wrapper over the same journal (the restarted agent).
    rs2 = _resilient(flaky, tmp_path)
    assert rs2.journal_backlog() == 2
    rs2.queue_length("q")
    assert rs2.journal_backlog() == 0
    rows = [r for r in raw.query_entities(names.TABLE_GOODPUT)
            if r["kind"] == "idle"]
    assert len(rows) == 2


def test_resilient_wal_survives_restart(tmp_path):
    flaky = FlakyStore(MemoryStateStore())
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    flaky.down = True
    rs.insert_entity(names.TABLE_GOODPUT, "testpool", "000$r",
                     {"kind": "idle", "start": 0, "end": 1})
    del rs  # the agent process dies with a backlog
    flaky.down = False
    rs2 = _resilient(flaky, tmp_path)
    assert rs2.journal_backlog() == 1
    rs2.queue_length("q")
    assert rs2.journal_backlog() == 0
    assert len(list(flaky.inner.query_entities(
        names.TABLE_GOODPUT))) == 1


def test_resilient_fresh_advisory_queues_behind_undrained_backlog(
        tmp_path):
    """Latch-close vs replay-drain race (review fix): until the
    backlog is fully drained, a fresh advisory write must NOT bypass
    the journal — the replay of its own entity's stale journaled
    value would overwrite it, moving heartbeat_at backwards and
    letting sibling nodes orphan-reclaim a live node's tasks."""
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    raw.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                      {"state": "idle"})
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    flaky.down = True
    rs.merge_entity(names.TABLE_NODES, "testpool", "n0",
                    {"heartbeat_at": 1.0})
    assert rs.journal_backlog() == 1
    del rs  # agent dies with the stale beat journaled
    flaky.down = False
    # Restarted wrapper: backlog loaded, store healthy, NO latch.
    rs2 = _resilient(flaky, tmp_path, probe_interval=3600.0)
    assert rs2.journal_backlog() == 1
    assert not rs2.outage_active()
    # Pin the drain mid-flight: a concurrent replay owns the lock.
    assert rs2._replay_lock.acquire(blocking=False)
    try:
        rs2.merge_entity(names.TABLE_NODES, "testpool", "n0",
                         {"heartbeat_at": 2.0})
        # The fresh beat queued BEHIND the stale backlog instead of
        # writing through it.
        assert raw.get_entity(
            names.TABLE_NODES, "testpool",
            "n0").get("heartbeat_at") is None
    finally:
        rs2._replay_lock.release()
    rs2.queue_length("q")
    assert rs2.journal_backlog() == 0
    # Newest value wins: the drain applied the coalesced/ordered
    # journal, never a stale-over-fresh overwrite.
    assert raw.get_entity(names.TABLE_NODES, "testpool",
                          "n0")["heartbeat_at"] == 2.0


def test_resilient_bounded_caps_critical_retry(tmp_path):
    """A bounded() caller (the agent heartbeat thread) gets
    StoreOutageError within its window instead of sleeping toward
    max_outage_seconds — one dark store must not park the thread
    that drives heartbeats, lease renewal and eviction kills (review
    fix). Outside the block the full ride-through still applies."""
    from batch_shipyard_tpu.state.resilient import StoreOutageError
    flaky = FlakyStore(MemoryStateStore())
    rs = _resilient(flaky, tmp_path, max_outage_seconds=900.0)
    flaky.down = True
    t0 = time.monotonic()
    with pytest.raises(StoreOutageError):
        with rs.bounded(0.3):
            rs.get_entity(names.TABLE_TASKS, "p$j", "t0")
    assert time.monotonic() - t0 < 2.0
    assert rs.outage_active()
    # Scoped: the same op outside the block rides the outage out.
    threading.Timer(0.2, lambda: setattr(flaky, "down",
                                         False)).start()
    assert rs.queue_length("q") == 0
    assert not rs.outage_active()


def test_resilient_replay_never_resurrects_deleted_node(tmp_path):
    """A journaled nodes-table upsert whose target the substrate
    deleted during the outage is dropped on replay, not re-created
    (review fix): upsert_entity re-creates unconditionally, and a
    resurrected row would be ghost capacity to federation _pool_facts
    and heimdall until something else garbage-collected it."""
    raw = MemoryStateStore()
    flaky = FlakyStore(raw)
    rs = _resilient(flaky, tmp_path, probe_interval=3600.0)
    raw.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                      {"state": "idle"})
    flaky.down = True
    rs.upsert_entity(names.TABLE_NODES, "testpool", "n0",
                     {"state": "idle", "heartbeat_at": 1.0})
    assert rs.journal_backlog() == 1
    # The pool is resized away mid-outage (writes through RAW: the
    # substrate's own store handle is not this wrapper).
    raw.delete_entity(names.TABLE_NODES, "testpool", "n0")
    flaky.down = False
    rs.queue_length("q")
    assert rs.journal_backlog() == 0
    with pytest.raises(NotFoundError):
        raw.get_entity(names.TABLE_NODES, "testpool", "n0")


def test_preempt_notice_deferred_until_stamp_stands():
    """defer_notice=True returns the notice-emitting closure instead
    of publishing eagerly (review fix): the sweep's post-write fence
    check can RETRACT a late-landing stamp, and an eagerly-emitted
    TASK_PREEMPT_NOTICE would survive the retraction as a phantom
    preemption in every consumer (drill invariant, heimdall,
    accounting)."""
    from batch_shipyard_tpu.goodput import events as goodput_events
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    store = MemoryStateStore()
    store.insert_entity(names.TABLE_TASKS,
                        names.task_pk("p", "j"), "t0",
                        {"state": "running", "spec": {}})

    def notices():
        return [r for r in store.query_entities(names.TABLE_GOODPUT)
                if r["kind"] == goodput_events.TASK_PREEMPT_NOTICE]

    emit = jobs_mgr.request_preemption(store, "p", "j", "t0",
                                       leader_epoch=7,
                                       defer_notice=True)
    assert callable(emit)  # stamp landed, notice withheld
    assert store.get_entity(
        names.TABLE_TASKS, names.task_pk("p", "j"),
        "t0")[names.TASK_COL_PREEMPT_REQUEST]["leader_epoch"] == 7
    assert notices() == []
    emit()
    assert len(notices()) == 1
    assert notices()[0]["attrs"]["leader_epoch"] == 7
    # The undeferred path (manual CLI preemptions) still emits
    # inline; re-stamping stays an idempotent no-op either way.
    assert jobs_mgr.request_preemption(store, "p", "j", "t0") is True
    assert len(notices()) == 1


def test_heimdall_exports_fed_elastic_lease_epoch():
    """The fed-elastic lease epoch rides shipyard_leader_epoch per
    federation (review fix): docs/30's lease table promises all
    THREE leases are observable, and the federation evaluator's
    double-fire (a double-fanned gang migration) is the least
    idempotent of them."""
    from batch_shipyard_tpu.monitor import heimdall
    store = MemoryStateStore()
    store.upsert_entity(names.TABLE_FEDERATIONS, "fed", "fedA",
                        {"pools": []})
    scope = "fed-fedA"
    lease = state_leases.LeaderLease(
        store,
        key=names.leader_lease_key(scope,
                                   state_leases.ROLE_FED_ELASTIC),
        epoch_key=names.leader_epoch_key(
            scope, state_leases.ROLE_FED_ELASTIC),
        owner="proc0", duration_seconds=5.0)
    epoch = lease.epoch()
    assert epoch is not None
    lines = heimdall._federation_lease_metrics(store)
    assert lines == [
        f'shipyard_leader_epoch{{lease="fed-elastic",'
        f'federation="fedA"}} {epoch}']


# --------------------------- adoption (unit) ---------------------------

def test_adoption_classifies_exited_task_without_rerun(tmp_path):
    """The 'still-valid claim, process already exited' adoption leg:
    a restarted agent finds a slot ledger whose pid is dead but
    whose exit-code sentinel says 0 — the task is classified
    completed through the normal path, retries untouched, instead of
    the reclaim-rerun."""
    from batch_shipyard_tpu.agent import task_runner
    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    from batch_shipyard_tpu.config import settings as settings_mod

    store = MemoryStateStore()
    conf = {"pool_specification": {
        "id": "adoptpool", "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    identity = NodeIdentity(
        pool_id="adoptpool", node_id="n0", node_index=0,
        hostname="n0", internal_ip="10.0.0.1")
    work_dir = str(tmp_path / "node")
    task_dir = os.path.join(work_dir, "tasks", "j1", "t1")
    os.makedirs(task_dir)
    os.makedirs(os.path.join(work_dir, "slots"))
    with open(os.path.join(task_dir, "stdout.txt"), "w",
              encoding="utf-8") as fh:
        fh.write("done\n")
    with open(os.path.join(task_dir, "stderr.txt"), "w",
              encoding="utf-8") as fh:
        fh.write("")
    with open(os.path.join(task_dir,
                           task_runner.EXIT_CODE_FILENAME), "w",
              encoding="utf-8") as fh:
        fh.write("0")
    # The predecessor's claim: running, owned by this node, with a
    # ledger naming a long-dead pid.
    spec = {"command": "echo done", "max_task_retries": 2}
    store.upsert_entity(names.TABLE_JOBS, "adoptpool", "j1",
                        {"state": "active"})
    store.upsert_entity(names.TABLE_TASKS, "adoptpool$j1", "t1",
                        {"state": "running", "node_id": "n0",
                         "retries": 0, "spec": spec})
    store.upsert_entity(names.TABLE_NODES, "adoptpool", "n0",
                        {"state": "running", "node_index": 0,
                         "heartbeat_at": time.time() - 1.5})
    with open(os.path.join(work_dir, "slots", "slot0.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"slot": 0, "job_id": "j1", "task_id": "t1",
                   "pid": 2 ** 22 + 12345, "runtime": "none",
                   "container": None, "task_dir": task_dir,
                   "command": "echo done", "env": {},
                   "started_at": "2026-01-01T00:00:00.000000Z"},
                  fh)
    # Nodeprep marker so start() takes the reboot-resume fast path.
    with open(os.path.join(work_dir, ".nodeprep_finished"), "w",
              encoding="utf-8") as fh:
        fh.write("x")
    agent = NodeAgent(store, identity, pool, work_dir=work_dir,
                      heartbeat_interval=0.2, poll_interval=0.05)
    agent.start()
    try:
        deadline = time.monotonic() + 10.0
        state = None
        while time.monotonic() < deadline:
            state = store.get_entity(names.TABLE_TASKS,
                                     "adoptpool$j1",
                                     "t1").get("state")
            if state == "completed":
                break
            time.sleep(0.05)
        assert state == "completed", state
        task = store.get_entity(names.TABLE_TASKS, "adoptpool$j1",
                                "t1")
        assert int(task.get("retries", 0) or 0) == 0
        # The adoption leg + restart span were recorded.
        kinds = [r["kind"] for r in store.query_entities(
            names.TABLE_GOODPUT, partition_key="adoptpool")]
        assert "adoption" in kinds, kinds
        # The slot ledger was retired after classification.
        assert not os.path.exists(
            os.path.join(work_dir, "slots", "slot0.json"))
    finally:
        agent.stop()
        agent.join(timeout=5.0)


def test_adoption_unknowable_container_exit_hands_back_to_reclaim(
        tmp_path):
    """Containerized adoption with an unlearnable outcome (no exit
    sentinel — only the runtime-'none' shell trailer writes one from
    inside the task's session — and no container left to ask): the
    task must NOT be classified as failed. It hands back through the
    orphan-reclaim semantics — pending, no retry consumed, neutral
    health (review fix: previously hard-coded exit -9)."""
    import subprocess as sp

    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    from batch_shipyard_tpu.config import settings as settings_mod

    store = MemoryStateStore()
    conf = {"pool_specification": {
        "id": "adoptpool", "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    identity = NodeIdentity(
        pool_id="adoptpool", node_id="n0", node_index=0,
        hostname="n0", internal_ip="10.0.0.1")
    work_dir = str(tmp_path / "node")
    task_dir = os.path.join(work_dir, "tasks", "j1", "t1")
    os.makedirs(task_dir)
    os.makedirs(os.path.join(work_dir, "slots"))
    spec = {"command": "echo run", "max_task_retries": 2}
    store.upsert_entity(names.TABLE_JOBS, "adoptpool", "j1",
                        {"state": "active"})
    store.upsert_entity(names.TABLE_TASKS, "adoptpool$j1", "t1",
                        {"state": "running", "node_id": "n0",
                         "retries": 0, "spec": spec})
    store.upsert_entity(names.TABLE_NODES, "adoptpool", "n0",
                        {"state": "running", "node_index": 0,
                         "heartbeat_at": time.time() - 1.5})
    # A live stand-in for the adopted docker-client pid; launched
    # start_new_session like every real task (the adoption pid-
    # identity guard requires a session leader), reaped on exit so
    # the watcher sees a genuinely-dead process, not a zombie.
    proc = sp.Popen(["sleep", "0.4"], start_new_session=True)
    threading.Thread(target=proc.wait, daemon=True).start()
    with open(os.path.join(work_dir, "slots", "slot0.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"slot": 0, "job_id": "j1", "task_id": "t1",
                   "pid": proc.pid, "runtime": "docker",
                   "container": "shipyard-adopt-gone",
                   "task_dir": task_dir, "command": "echo run",
                   "env": {},
                   "started_at": "2026-01-01T00:00:00.000000Z"},
                  fh)
    with open(os.path.join(work_dir, ".nodeprep_finished"), "w",
              encoding="utf-8") as fh:
        fh.write("x")
    agent = NodeAgent(store, identity, pool, work_dir=work_dir,
                      heartbeat_interval=0.2, poll_interval=0.05)
    agent.start()
    try:
        deadline = time.monotonic() + 15.0
        state = None
        while time.monotonic() < deadline:
            state = store.get_entity(names.TABLE_TASKS,
                                     "adoptpool$j1",
                                     "t1").get("state")
            if state == "pending":
                break
            time.sleep(0.05)
        task = store.get_entity(names.TABLE_TASKS, "adoptpool$j1",
                                "t1")
        assert task.get("state") == "pending", task.get("state")
        assert task.get("node_id") is None
        # Reclaim semantics: repeat work, never budget or health.
        assert int(task.get("retries", 0) or 0) == 0
        node = store.get_entity(names.TABLE_NODES, "adoptpool",
                                "n0")
        assert float(node.get("health", 1.0) or 1.0) >= 1.0
        assert not os.path.exists(
            os.path.join(work_dir, "slots", "slot0.json"))
    finally:
        agent.stop()
        agent.join(timeout=5.0)


def test_adopted_task_wedge_watchdog_enforced(tmp_path):
    """Adoption re-arms the task's runtime limits (review fix): the
    original run_task watchdog died with the old agent, so a wedged
    adopted task must still be killed and classified — not hold its
    slot (and the node's capacity) forever."""
    import subprocess as sp

    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    from batch_shipyard_tpu.config import settings as settings_mod

    store = MemoryStateStore()
    conf = {"pool_specification": {
        "id": "adoptpool", "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    identity = NodeIdentity(
        pool_id="adoptpool", node_id="n0", node_index=0,
        hostname="n0", internal_ip="10.0.0.1")
    work_dir = str(tmp_path / "node")
    task_dir = os.path.join(work_dir, "tasks", "j1", "t1")
    os.makedirs(task_dir)
    os.makedirs(os.path.join(work_dir, "slots"))
    # A beat file whose last beat predates the deadline by far: the
    # adopted task is wedged from the watcher's first look.
    beat_file = str(tmp_path / "progress_beat")
    with open(beat_file, "w", encoding="utf-8") as fh:
        fh.write("")
    os.utime(beat_file, (time.time() - 100, time.time() - 100))
    spec = {"command": "sleep 30", "max_task_retries": 0,
            "progress_deadline_seconds": 0.5}
    store.upsert_entity(names.TABLE_JOBS, "adoptpool", "j1",
                        {"state": "active"})
    store.upsert_entity(names.TABLE_TASKS, "adoptpool$j1", "t1",
                        {"state": "running", "node_id": "n0",
                         "retries": 0, "spec": spec})
    store.upsert_entity(names.TABLE_NODES, "adoptpool", "n0",
                        {"state": "running", "node_index": 0,
                         "heartbeat_at": time.time() - 1.5})
    # Own session group: _hard_kill_task_group SIGKILLs the pgid.
    proc = sp.Popen(["sleep", "30"], start_new_session=True)
    try:
        with open(os.path.join(work_dir, "slots", "slot0.json"),
                  "w", encoding="utf-8") as fh:
            json.dump({"slot": 0, "job_id": "j1", "task_id": "t1",
                       "pid": proc.pid, "runtime": "none",
                       "container": None, "task_dir": task_dir,
                       "command": "sleep 30",
                       "env": {"SHIPYARD_PROGRESS_FILE": beat_file},
                       "started_at": "2026-01-01T00:00:00.000000Z"},
                      fh)
        with open(os.path.join(work_dir, ".nodeprep_finished"), "w",
                  encoding="utf-8") as fh:
            fh.write("x")
        agent = NodeAgent(store, identity, pool, work_dir=work_dir,
                          heartbeat_interval=0.2,
                          poll_interval=0.05)
        agent.start()
        try:
            deadline = time.monotonic() + 10.0
            state = None
            while time.monotonic() < deadline:
                state = store.get_entity(names.TABLE_TASKS,
                                         "adoptpool$j1",
                                         "t1").get("state")
                if state == "failed":
                    break
                time.sleep(0.05)
            assert state == "failed", state
            # The wedged process really died (poll() reaps it).
            kill_deadline = time.monotonic() + 5.0
            while proc.poll() is None and \
                    time.monotonic() < kill_deadline:
                time.sleep(0.05)
            assert proc.poll() is not None
            assert not os.path.exists(
                os.path.join(work_dir, "slots", "slot0.json"))
        finally:
            agent.stop()
            agent.join(timeout=5.0)
    finally:
        if proc.poll() is None:
            proc.kill()


def _bare_agent(tmp_path, store, pool_id="adoptpool",
                job_state_ttl=5.0):
    """A constructed-but-not-started NodeAgent over a fake pool —
    for driving adoption/forwarding methods directly, without the
    heartbeat/worker threads."""
    from batch_shipyard_tpu.agent.node_agent import (
        NodeAgent, NodeIdentity)
    from batch_shipyard_tpu.config import settings as settings_mod

    conf = {"pool_specification": {
        "id": pool_id, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    identity = NodeIdentity(
        pool_id=pool_id, node_id="n0", node_index=0,
        hostname="n0", internal_ip="10.0.0.1")
    work_dir = str(tmp_path / "node")
    os.makedirs(os.path.join(work_dir, "slots"), exist_ok=True)
    return NodeAgent(store, identity, pool, work_dir=work_dir,
                     heartbeat_interval=0.2, poll_interval=0.05,
                     job_state_ttl=job_state_ttl)


def test_gang_member_ledger_fenced_on_restart(tmp_path):
    """A gang member's slot ledger is written at launch and a
    restarted agent FENCES (kills) the leftover live process instead
    of adopting it: the rendezvous context died with the old agent,
    so the gang requeue owns the rerun — and must never share the
    task dir with a live predecessor (the double-execution class)."""
    import subprocess as sp

    from batch_shipyard_tpu.agent.node_agent import NodeAgent

    store = MemoryStateStore()
    agent = _bare_agent(tmp_path, store)
    proc = sp.Popen(["sleep", "30"], start_new_session=True)
    try:
        ledger = {"slot": 0, "job_id": "j1", "task_id": "t1",
                  "pid": proc.pid, "gang": True,
                  "pid_start_ticks":
                      NodeAgent._proc_start_ticks(proc.pid),
                  "runtime": "none", "container": None,
                  "task_dir": str(tmp_path / "node" / "tasks"
                                  / "j1" / "t1"),
                  "command": "sleep 30", "env": {},
                  "started_at": "2026-01-01T00:00:00.000000Z"}
        path = os.path.join(agent.work_dir, "slots", "slot0.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(ledger, fh)
        adopted = agent._adopt_restart_state()
        assert adopted == 0
        # Fenced: the member process is dead, the ledger retired —
        # purely locally, no store rows were needed or touched.
        deadline = time.monotonic() + 5.0
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert proc.poll() is not None
        assert not os.path.exists(path)
        assert not agent._adopted_slots
    finally:
        if proc.poll() is None:
            proc.kill()


def test_adoption_never_touches_a_recycled_pid(tmp_path):
    """Pid-identity guard: a ledgered pid that now belongs to a
    STRANGER (not a session leader — the shape of an OS-recycled
    number, since every task launches start_new_session) is treated
    as dead: no adoption, no kill, ledger retired so the ordinary
    reclaim-rerun owns the task."""
    import subprocess as sp

    store = MemoryStateStore()
    agent = _bare_agent(tmp_path, store)
    store.upsert_entity(names.TABLE_JOBS, "adoptpool", "j1",
                        {"state": "active"})
    store.upsert_entity(names.TABLE_TASKS, "adoptpool$j1", "t1",
                        {"state": "running", "node_id": "n0",
                         "retries": 0,
                         "spec": {"command": "sleep 30"}})
    # NOT start_new_session: pgid != pid, like a recycled number.
    proc = sp.Popen(["sleep", "30"])
    try:
        path = os.path.join(agent.work_dir, "slots", "slot0.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"slot": 0, "job_id": "j1", "task_id": "t1",
                       "pid": proc.pid, "runtime": "none",
                       "container": None,
                       "task_dir": str(tmp_path / "t"),
                       "command": "sleep 30", "env": {},
                       "started_at": "2026-01-01T00:00:00.000000Z"},
                      fh)
        adopted = agent._adopt_restart_state()
        assert adopted == 0
        # The stranger was NOT killed and nothing waits on it.
        assert proc.poll() is None
        assert not os.path.exists(path)
        assert not agent._adopted_slots
    finally:
        proc.kill()
        proc.wait(timeout=5.0)


def test_stale_epoch_preempt_stamp_held_until_confirmed(tmp_path):
    """Consumer-side fence for the author-retraction race: a stamp
    whose leader_epoch predates the sweep lease's current term is
    held for one confirmation cycle before delivery. A stamp the
    author retracts during the hold is never delivered (no spurious
    drain); one that survives confirmation IS delivered (a
    legitimate pre-turnover stamp must still drain its victim)."""
    from batch_shipyard_tpu.agent.node_agent import _AdoptedProc
    from batch_shipyard_tpu.utils import util

    store = MemoryStateStore()
    agent = _bare_agent(tmp_path, store, job_state_ttl=0.0)
    epoch_key = names.leader_epoch_key(
        "adoptpool", state_leases.ROLE_PREEMPT_SWEEP)
    # Two terms recorded: current epoch is 2; stamps carrying 1 are
    # stale.
    body = json.dumps({"owner": "n9", "lease": "x"}).encode("utf-8")
    store.put_object(epoch_key, body)
    assert store.put_object(epoch_key, body) == 2
    task_dir = os.path.join(agent.work_dir, "tasks", "j1", "t1")
    os.makedirs(task_dir)
    store.upsert_entity(names.TABLE_JOBS, "adoptpool", "j1",
                        {"state": "active"})

    def _stamp(requested_at, epoch):
        request = {"reason": "r", "requested_at": requested_at}
        if epoch is not None:
            request["leader_epoch"] = epoch
        store.upsert_entity(
            names.TABLE_TASKS, "adoptpool$j1", "t1",
            {"state": "running", "node_id": "n0", "retries": 0,
             "spec": {"command": "sleep 30"},
             names.TASK_COL_PREEMPT_REQUEST: request})

    request_file = os.path.join(task_dir, "preempt_request.json")
    agent._live_procs[("j1", "t1")] = _AdoptedProc(None)
    # Round 1: stale stamp, retracted during the hold -> never
    # delivered.
    _stamp(util.datetime_utcnow_iso(), epoch=1)
    agent._forward_preempt_requests()
    assert not os.path.exists(request_file)  # held, not delivered
    store.merge_entity(names.TABLE_TASKS, "adoptpool$j1", "t1",
                       {names.TASK_COL_PREEMPT_REQUEST: None})
    time.sleep(0.6)
    agent._forward_preempt_requests()
    assert not os.path.exists(request_file)
    # Round 2: stale stamp that SURVIVES confirmation is delivered.
    _stamp(util.datetime_utcnow_iso(), epoch=1)
    agent._forward_preempt_requests()
    assert not os.path.exists(request_file)
    time.sleep(0.6)
    agent._forward_preempt_requests()
    assert os.path.exists(request_file)
    os.remove(request_file)
    os.remove(request_file + ".delivered")
    # Epoch-less (manual jobs preempt) stamps deliver immediately.
    _stamp(util.datetime_utcnow_iso(), epoch=None)
    agent._forward_preempt_requests()
    assert os.path.exists(request_file)


# ------------------------------- drills --------------------------------

def test_store_outage_drill():
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_store_outage_drill(seed=0)
    assert report["invariants"]["ok"] is True
    assert report["invariants"]["retries"] == 0
    assert report["invariants"]["store_outage_seconds"] > 0


def test_leader_partition_drill():
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_leader_partition_drill(seed=0)
    inv = report["invariants"]
    assert inv["ok"] is True
    assert inv["preempt_notices"] == 1
    assert inv["stamp_epoch"] == inv["epoch_after"]
    assert inv["epoch_after"] > inv["epoch_before"]
    assert len(inv["lease_holders"]) == 1


def test_agent_restart_drill():
    from batch_shipyard_tpu.chaos import drill
    report = drill.run_agent_restart_drill(seed=0)
    inv = report["invariants"]
    assert inv["ok"] is True
    assert inv["task_starts"] == 1
    assert inv["retries"] == 0
    assert inv["adoption_seconds"] > 0

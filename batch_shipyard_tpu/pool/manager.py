"""Pool lifecycle: create, wait-ready with recovery, resize, delete.

Reference analog: convoy/batch.py pool ops — create_pool(:921),
wait_for_pool_ready(:861) and the _block_for_nodes_ready hot loop
(:625) that classifies resize errors, reboots start-task-failed nodes
(reboot_on_start_task_failed) and deletes+recreates unusable nodes
(attempt_recovery_on_unusable). TPU twist: recovery granularity is the
pod slice, not the single VM (substrate.recreate_slice).
"""

from __future__ import annotations

import json
import time
from typing import Optional

from batch_shipyard_tpu.agent import cascade, perf
from batch_shipyard_tpu.config.settings import (
    GlobalSettings, PoolSettings)
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    NotFoundError, StateStore)
from batch_shipyard_tpu.substrate.base import ComputeSubstrate, NodeInfo
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

READY_STATES = ("idle", "running")
FAILED_STATES = ("start_task_failed", "unusable")


class PoolExistsError(RuntimeError):
    pass


class PoolNotFoundError(RuntimeError):
    pass


class PoolAllocationError(RuntimeError):
    pass


def pool_exists(store: StateStore, pool_id: str) -> bool:
    try:
        store.get_entity(names.TABLE_POOLS, "pools", pool_id)
        return True
    except NotFoundError:
        return False


def get_pool(store: StateStore, pool_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_POOLS, "pools", pool_id)
    except NotFoundError:
        raise PoolNotFoundError(pool_id)


def list_pools(store: StateStore) -> list[dict]:
    return list(store.query_entities(names.TABLE_POOLS,
                                     partition_key="pools"))


def list_nodes(store: StateStore, pool_id: str) -> list[NodeInfo]:
    out = []
    for row in store.query_entities(names.TABLE_NODES,
                                    partition_key=pool_id):
        out.append(NodeInfo(
            node_id=row["_rk"], state=row.get("state", "unknown"),
            hostname=row.get("hostname", ""),
            internal_ip=row.get("internal_ip", ""),
            node_index=int(row.get("node_index", 0)),
            slice_index=int(row.get("slice_index", 0)),
            worker_index=int(row.get("worker_index", 0)),
            health=float(row.get(names.NODE_COL_HEALTH, 1.0) or 0.0),
            quarantined=bool(row.get(names.NODE_COL_QUARANTINED,
                                     False))))
    return sorted(out, key=lambda n: n.node_index)


def create_pool(store: StateStore, substrate: ComputeSubstrate,
                pool: PoolSettings, global_conf: GlobalSettings,
                pool_config_raw: Optional[dict] = None,
                wait: bool = True) -> list[NodeInfo]:
    """Provision a pool end-to-end (action_pool_add path,
    fleet.py:3390)."""
    if pool_exists(store, pool.id):
        raise PoolExistsError(f"pool {pool.id} exists")
    store.insert_entity(names.TABLE_POOLS, "pools", pool.id, {
        "state": "creating",
        "substrate": pool.substrate,
        "spec": pool_config_raw or {},
        "created_at": util.datetime_utcnow_iso(),
    })
    perf.emit(store, pool.id, "-", "pool", "create.start")
    # Image manifest for cascade before nodes boot.
    cascade.populate_global_resources(
        store, pool.id, list(global_conf.docker_images),
        list(global_conf.singularity_images),
        global_conf.concurrent_source_downloads,
        registries=list(
            getattr(global_conf, "docker_registries", ()) or ()))
    try:
        substrate.allocate_pool(pool)
    except Exception as exc:
        store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                           {"state": "allocation_failed",
                            "error": str(exc)})
        raise PoolAllocationError(str(exc)) from exc
    if not wait:
        return []
    nodes = wait_for_pool_ready(store, substrate, pool)
    store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                       {"state": "ready"})
    perf.emit(store, pool.id, "-", "pool", "create.end")
    return nodes


def wait_for_pool_ready(store: StateStore, substrate: ComputeSubstrate,
                        pool: PoolSettings,
                        poll_interval: float = 0.25) -> list[NodeInfo]:
    """_block_for_nodes_ready analog (batch.py:625): poll node states,
    apply recovery knobs, raise on timeout with diagnostics."""
    deadline = time.monotonic() + pool.max_wait_time_seconds
    expected = pool.current_node_count
    rebooted_slices: set[int] = set()
    recovered_slices: set[int] = set()
    while True:
        nodes = list_nodes(store, pool.id)
        ready = [n for n in nodes if n.state in READY_STATES]
        if len(ready) >= expected:
            return nodes
        for node in nodes:
            if node.state == "start_task_failed":
                if (pool.reboot_on_start_task_failed and
                        node.slice_index not in rebooted_slices):
                    logger.warning(
                        "node %s start task failed; recreating slice %d",
                        node.node_id, node.slice_index)
                    rebooted_slices.add(node.slice_index)
                    substrate.recreate_slice(pool, node.slice_index)
                elif not pool.reboot_on_start_task_failed:
                    raise PoolAllocationError(
                        f"node {node.node_id} start task failed "
                        f"(reboot_on_start_task_failed disabled); "
                        f"stdout/stderr under "
                        f"{names.node_log_key(pool.id, node.node_id, '')}")
            elif node.state == "unusable":
                if (pool.attempt_recovery_on_unusable and
                        node.slice_index not in recovered_slices):
                    logger.warning(
                        "node %s unusable; recreating slice %d",
                        node.node_id, node.slice_index)
                    recovered_slices.add(node.slice_index)
                    substrate.recreate_slice(pool, node.slice_index)
                elif not pool.attempt_recovery_on_unusable:
                    raise PoolAllocationError(
                        f"node {node.node_id} unusable "
                        f"(attempt_recovery_on_unusable disabled)")
        # Allocation errors recorded by the substrate: fatal ones
        # (quota/permission/config) can never succeed; 'other_zone'
        # retries (stockout) also fail fast because the zone is fixed
        # by credentials — waiting out the pool timeout cannot help,
        # the operator must pick another zone. Only 'backoff' errors
        # (transient service trouble) keep polling.
        entity = get_pool(store, pool.id)
        if entity.get("allocation_error_fatal") or \
                entity.get("allocation_error_retry") == "other_zone":
            raise PoolAllocationError(
                f"{entity['allocation_error']} "
                f"[kind={entity.get('allocation_error_kind')}, "
                f"retry={entity.get('allocation_error_retry')}]")
        if time.monotonic() > deadline:
            states = {n.node_id: n.state for n in nodes}
            raise PoolAllocationError(
                f"pool {pool.id} not ready after "
                f"{pool.max_wait_time_seconds}s: {states}")
        time.sleep(poll_interval)


def resize_pool(store: StateStore, substrate: ComputeSubstrate,
                pool: PoolSettings, num_slices: int,
                wait: bool = True) -> None:
    store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                       {"state": "resizing"})
    # A resize may rewrite the pool spec (shard autoscale rides the
    # same entity): drop this process's cached task-queue shard count
    # so submitters re-read it instead of routing on a stale fan-out
    # for a full TTL.
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    jobs_mgr.invalidate_pool_queue_shards(store, pool.id)
    substrate.resize_pool(pool, num_slices)
    if wait:
        if pool.tpu is not None:
            expected = num_slices * pool.tpu.workers_per_slice
        else:
            expected = num_slices
        deadline = time.monotonic() + pool.max_wait_time_seconds
        while True:
            ready = [n for n in list_nodes(store, pool.id)
                     if n.state in READY_STATES]
            if len(ready) >= expected:
                break
            if time.monotonic() > deadline:
                raise PoolAllocationError(
                    f"resize of {pool.id} timed out")
            time.sleep(0.25)
    store.merge_entity(names.TABLE_POOLS, "pools", pool.id,
                       {"state": "ready"})


def delete_pool(store: StateStore, substrate: ComputeSubstrate,
                pool_id: str) -> None:
    get_pool(store, pool_id)  # raises if missing
    substrate.deallocate_pool(pool_id)
    # Clear jobs/tasks state for the pool.
    for job in list(store.query_entities(names.TABLE_JOBS,
                                         partition_key=pool_id)):
        _purge_job(store, pool_id, job["_rk"])
    store.delete_entity(names.TABLE_POOLS, "pools", pool_id)


def _purge_job(store: StateStore, pool_id: str, job_id: str) -> None:
    pk = names.task_pk(pool_id, job_id)
    for task in list(store.query_entities(names.TABLE_TASKS,
                                          partition_key=pk)):
        store.delete_entity(names.TABLE_TASKS, pk, task["_rk"])
    for row in list(store.query_entities(names.TABLE_JOBPREP,
                                         partition_key=pk)):
        store.delete_entity(names.TABLE_JOBPREP, pk, row["_rk"])
    try:
        store.delete_entity(names.TABLE_JOBS, pool_id, job_id)
    except NotFoundError:
        pass


def pool_stats(store: StateStore, pool_id: str) -> dict:
    """pool stats analog (batch.py:1460)."""
    nodes = list_nodes(store, pool_id)
    by_state: dict[str, int] = {}
    for node in nodes:
        by_state[node.state] = by_state.get(node.state, 0) + 1
    jobs = list(store.query_entities(names.TABLE_JOBS,
                                     partition_key=pool_id))
    task_counts = {"pending": 0, "running": 0, "completed": 0,
                   "failed": 0, "blocked": 0, "assigned": 0,
                   names.TASK_STATE_QUARANTINED: 0}
    for job in jobs:
        pk = names.task_pk(pool_id, job["_rk"])
        for task in store.query_entities(names.TABLE_TASKS,
                                         partition_key=pk):
            state = task.get("state", "pending")
            task_counts[state] = task_counts.get(state, 0) + 1
    return {
        "pool_id": pool_id,
        "nodes": {"total": len(nodes), "by_state": by_state},
        "jobs": len(jobs),
        "tasks": task_counts,
    }


def send_control(store: StateStore, pool_id: str, node_id: str,
                 message: dict) -> None:
    store.put_message(names.control_queue(pool_id, node_id),
                      json.dumps(message).encode())


def _send_control_request(store: StateStore, pool_id: str,
                          node_id: str, message: dict,
                          timeout: float) -> str:
    """Enqueue a request/reply control verb and return its reply key.
    The message carries expires_at so a verb that outlives its caller
    is DROPPED by the agent instead of executing minutes later — a
    timed-out zap must not kill tasks after the operator moved on."""
    import uuid as uuid_mod
    reply_key = names.control_reply_key(pool_id, node_id,
                                        uuid_mod.uuid4().hex[:12])
    send_control(store, pool_id, node_id,
                 dict(message, reply_key=reply_key,
                      expires_at=time.time() + timeout))
    return reply_key


def _poll_reply(store: StateStore, reply_key: str) -> Optional[dict]:
    try:
        payload = store.get_object(reply_key)
    except NotFoundError:
        return None
    try:
        store.delete_object(reply_key)
    except NotFoundError:
        pass
    return json.loads(payload.decode())


def send_control_and_wait(store: StateStore, pool_id: str,
                          node_id: str, message: dict,
                          timeout: float = 30.0,
                          poll_interval: float = 0.1) -> dict:
    """Request/reply control verb: attach a reply key, enqueue, poll
    the object store for the agent's answer (nodes ps/zap/prune ride
    this — the agent answers over the state store, no ssh needed;
    reference equivalent is docker-ps-over-ssh, convoy/fleet.py:2468).
    Raises TimeoutError if the node never answers (offline node); the
    queued verb then expires unexecuted (see _send_control_request)."""
    reply_key = _send_control_request(store, pool_id, node_id,
                                      message, timeout)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        reply = _poll_reply(store, reply_key)
        if reply is not None:
            return reply
        time.sleep(poll_interval)
    raise TimeoutError(
        f"node {node_id} did not answer {message.get('type')} "
        f"within {timeout:.0f}s (offline?)")


def get_node(store: StateStore, pool_id: str, node_id: str) -> NodeInfo:
    for node in list_nodes(store, pool_id):
        if node.node_id == node_id:
            return node
    raise PoolNotFoundError(f"node {node_id} not found in {pool_id}")


def node_counts(store: StateStore, pool_id: str) -> dict:
    """Node-state histogram (pool nodes count analog, reference
    shipyard.py:1868 / convoy/fleet.py node state counts)."""
    counts: dict = {}
    nodes = list_nodes(store, pool_id)
    for node in nodes:
        counts[node.state] = counts.get(node.state, 0) + 1
    return {"pool_id": pool_id, "total": len(nodes),
            "by_state": dict(sorted(counts.items()))}


def remote_login_settings(store: StateStore,
                          substrate: ComputeSubstrate,
                          pool_id: str,
                          node_id: Optional[str] = None) -> list[dict]:
    """(node, ip, port) for every node — or one — in the pool
    (pool nodes grls analog, reference convoy/batch.py:3074
    get_remote_login_settings)."""
    nodes = list_nodes(store, pool_id)
    if node_id is not None:
        nodes = [n for n in nodes if n.node_id == node_id]
        if not nodes:
            raise PoolNotFoundError(
                f"node {node_id} not found in {pool_id}")
    out = []
    for node in nodes:
        login = substrate.get_remote_login(pool_id, node.node_id)
        out.append({
            "node_id": node.node_id, "state": node.state,
            "ip": login[0] if login else None,
            "port": login[1] if login else None,
        })
    return out


def reboot_node(store: StateStore, substrate: ComputeSubstrate,
                pool: PoolSettings, node_id: str) -> int:
    """Reboot a node (pool nodes reboot analog, reference
    shipyard.py:1882). TPU recovery granularity is the pod slice —
    all workers of the node's slice are recreated together (a lone
    worker VM cannot rejoin an ICI mesh). Returns the slice index."""
    node = get_node(store, pool.id, node_id)
    logger.info("rebooting node %s => recreating slice %d",
                node_id, node.slice_index)
    substrate.recreate_slice(pool, node.slice_index)
    return node.slice_index


def delete_node(store: StateStore, substrate: ComputeSubstrate,
                pool: PoolSettings, node_id: str) -> int:
    """Remove a node from the pool (pool nodes del analog, reference
    shipyard.py:1795). Slice-granular like reboot: the node's whole
    slice is deallocated and NOT replaced — the pool shrinks by one
    slice (use pool resize to grow back). Returns the slice index."""
    node = get_node(store, pool.id, node_id)
    logger.info("deleting node %s => deallocating slice %d",
                node_id, node.slice_index)
    substrate.deallocate_slice(pool, node.slice_index)
    return node.slice_index


def _control_fanout(store: StateStore, pool_id: str, message: dict,
                    node_id: Optional[str] = None,
                    timeout: float = 30.0,
                    poll_interval: float = 0.1) -> list[dict]:
    """Fan a request/reply verb to node(s): non-ready nodes are
    reported immediately instead of waited on, all requests are
    enqueued up front, and the replies poll under ONE shared deadline
    — wall clock is O(timeout), not O(nodes x timeout)."""
    nodes = list_nodes(store, pool_id)
    if node_id is not None:
        nodes = [n for n in nodes if n.node_id == node_id]
        if not nodes:
            raise PoolNotFoundError(
                f"node {node_id} not found in {pool_id}")
    replies: dict[str, dict] = {}
    pending: dict[str, str] = {}
    for node in nodes:
        if node.state not in READY_STATES:
            replies[node.node_id] = {
                "node_id": node.node_id,
                "error": f"node not ready (state={node.state})"}
            continue
        pending[node.node_id] = _send_control_request(
            store, pool_id, node.node_id, dict(message), timeout)
    deadline = time.monotonic() + timeout
    while pending and time.monotonic() < deadline:
        for nid, reply_key in list(pending.items()):
            reply = _poll_reply(store, reply_key)
            if reply is not None:
                replies[nid] = reply
                del pending[nid]
        if pending:
            time.sleep(poll_interval)
    for nid in pending:
        replies[nid] = {
            "node_id": nid,
            "error": (f"node {nid} did not answer "
                      f"{message.get('type')} within {timeout:.0f}s "
                      f"(offline?)")}
    return [replies[n.node_id] for n in nodes]


def nodes_ps(store: StateStore, pool_id: str,
             node_id: Optional[str] = None,
             timeout: float = 30.0) -> list[dict]:
    """Running tasks/containers per node (pool nodes ps analog)."""
    return _control_fanout(store, pool_id, {"type": "ps"},
                           node_id, timeout)


def nodes_zap(store: StateStore, pool_id: str,
              node_id: Optional[str] = None,
              timeout: float = 30.0) -> list[dict]:
    """Kill all live task processes/containers per node (pool nodes
    zap analog, reference shipyard.py:1906)."""
    return _control_fanout(store, pool_id, {"type": "zap"},
                           node_id, timeout)


def nodes_prune(store: StateStore, pool_id: str,
                node_id: Optional[str] = None,
                timeout: float = 30.0) -> list[dict]:
    """Prune unreferenced image cache entries per node (pool nodes
    prune analog, reference shipyard.py:1919)."""
    return _control_fanout(store, pool_id, {"type": "prune"},
                           node_id, timeout)

"""Per-host restore planning: which checkpoint shards does host m of
an M-host target mesh actually need?

The multi-host leg of reshard-on-restore (parallel/sharding.py): a
checkpoint saved by an N-host gang holds N contiguous shards per
sharded leading axis (the `.MESH` sidecar records that source
layout). When the gang re-forms at M hosts, restoring the FULL array
on every host — the single-host PR 10 behavior — multiplies restore
IO by M and, on real pods, blows host RAM for any model that needed
sharding in the first place. The plan computed here is the
intersection: for each target host, the source shards (and the slice
of each) that overlap the index range its addressable devices own.

Deliberately jax-free and stdlib-only: the same math drives

  * ``sharding.reshard_on_restore``'s per-host read path (where the
    target ranges come from the real NamedSharding index maps — the
    1-D contiguous case below is cross-checked against jax's maps in
    tests/test_fleet_elasticity.py), and
  * ``workloads/reshard_probe.py``, the drill trainer whose gang
    instances read exactly the shard files this plan names (the
    host_loss_resize chaos drill asserts the reads match the plan).

Shards are the jax convention: an axis of size ``dim`` split over
``parts`` equal contiguous blocks (divisibility required, exactly as
jax requires it for a sharded axis).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class ShardRead:
    """One read: take ``[lo, hi)`` (source-shard-local indices) from
    source shard ``shard`` and place it at ``[dst_lo, dst_lo + hi -
    lo)`` of the target host's block."""

    shard: int
    lo: int
    hi: int
    dst_lo: int


def shard_ranges(dim: int, parts: int) -> list[tuple[int, int]]:
    """The ``parts`` contiguous [lo, hi) blocks of an axis of size
    ``dim`` (the jax even-split convention; raises on indivisible
    axes exactly like a jax sharding would)."""
    if parts <= 0:
        raise ValueError(f"parts must be positive, got {parts}")
    if dim % parts:
        raise ValueError(
            f"axis of size {dim} does not split over {parts} shards")
    block = dim // parts
    return [(k * block, (k + 1) * block) for k in range(parts)]


def host_reads(dim: int, source_parts: int, target_parts: int,
               target_index: int) -> list[ShardRead]:
    """The reads target host ``target_index`` (of ``target_parts``)
    must issue against a checkpoint laid out as ``source_parts``
    shards — each read names a source shard and the slice of it that
    overlaps this host's target block. Covers the whole target block
    exactly once, in order."""
    if not 0 <= target_index < target_parts:
        raise ValueError(
            f"target_index {target_index} out of range "
            f"[0, {target_parts})")
    t_lo, t_hi = shard_ranges(dim, target_parts)[target_index]
    reads: list[ShardRead] = []
    for shard, (s_lo, s_hi) in enumerate(
            shard_ranges(dim, source_parts)):
        lo = max(t_lo, s_lo)
        hi = min(t_hi, s_hi)
        if hi <= lo:
            continue
        reads.append(ShardRead(shard=shard, lo=lo - s_lo,
                               hi=hi - s_lo, dst_lo=lo - t_lo))
    return reads


def plan(dim: int, source_parts: int,
         target_parts: int) -> dict[int, list[ShardRead]]:
    """The full N->M plan: target host index -> its reads. Every
    source element is read by at least one host, and each host reads
    only what its block needs (the two invariants the drill
    asserts)."""
    return {m: host_reads(dim, source_parts, target_parts, m)
            for m in range(target_parts)}


def read_fraction(dim: int, source_parts: int, target_parts: int,
                  target_index: int) -> float:
    """Fraction of the axis this host reads — the honesty number the
    restore path logs (1/M for an even resize; 1.0 would mean the
    plan degenerated to the full-array restore)."""
    reads = host_reads(dim, source_parts, target_parts, target_index)
    return sum(r.hi - r.lo for r in reads) / float(dim)

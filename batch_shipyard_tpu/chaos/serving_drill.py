"""Serving-tier chaos drills: replica kill, replica drain, router
restart.

The batch drills (chaos/drill.py) prove the scheduler's recovery
layer; these prove the SERVING fleet's (models/server.py drain
ladder, models/router.py mid-stream recovery). Each drill stands up a
real two-replica fleet — in-process ServingFrontEnds over tiny fp32
CPU engines, a real ServingRouter, real HTTP streaming clients —
replays one seeded injection from a ChaosPlan, and asserts the
serving acceptance invariants:

  * ZERO lost requests: every client stream ends with a final result
    line, and the router's lost_streams counter stays 0,
  * EXACTLY-ONCE token delivery: every client's token indexes are
    contiguous from 0 with no duplicates across the failover, and the
    fleet's completed-decode count equals the request count (no
    request ever decoded to completion twice),
  * BYTE-IDENTICAL greedy streams: the tokens a client assembles
    across the fault equal a clean replica's greedy decode of the
    same request, token for token,
  * the ``serving_recovery`` goodput leg is populated with the
    measured recovery windows and the partition stays exact.

Greedy decode is deterministic, so the byte-identical yardstick is
computed once per drill from an untouched reference replica. The
engines are throttled (a small sleep per decode step) so the seeded
injection provably lands MID-stream — every drill asserts its fault
was non-vacuous (recoveries >= 1, resumed_tokens strictly inside
(0, max_new_tokens)).

Used by `shipyard chaos drill --serve-kill|--serve-drain|
--serve-router` and the serving_resilience bench phase.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import os
import random
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from batch_shipyard_tpu.chaos.drill import _assert_partition_exact
from batch_shipyard_tpu.chaos.plan import ChaosPlan
from batch_shipyard_tpu.goodput import events as gp_events
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

POOL_ID = "serving-drill"


# ------------------------------ harness --------------------------------

def _build_fleet(num_replicas: int, step_delay: float,
                 **front_kwargs):
    """A tiny fp32 serving fleet on the CPU fakepod shape: shared
    params (greedy decode is then identical across replicas), one
    throttled engine per front end so injections land mid-stream."""
    import jax
    import jax.numpy as jnp

    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models import transformer as tfm
    from batch_shipyard_tpu.models.server import ServingFrontEnd

    cfg = tfm.TransformerConfig(
        vocab_size=97, d_model=32, n_layers=2, n_heads=2, d_head=16,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32)
    model = tfm.TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(7),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    fronts = []
    for _ in range(num_replicas):
        engine = serving.ContinuousBatcher(cfg, params, num_slots=2,
                                           max_decode_len=64)
        if step_delay:
            _throttle(engine, step_delay)
        fronts.append(
            ServingFrontEnd(engine, port=0, **front_kwargs).start())
    return cfg, params, fronts


def _throttle(engine, delay: float) -> None:
    """Slow the decode loop (a sleep per engine step) so a drill's
    streams are provably still live when its injection fires — the
    non-vacuousness every invariant depends on."""
    step = engine.step

    def slow_step():
        time.sleep(delay)
        return step()

    engine.step = slow_step


def _reference_outputs(cfg, params, specs: list[dict]) -> dict:
    """The byte-identical yardstick: a clean, unthrottled replica
    decodes every drill request fault-free; greedy decode is
    deterministic, so whatever the faulted fleet assembles must equal
    these tokens exactly."""
    from batch_shipyard_tpu.models import serving
    from batch_shipyard_tpu.models.server import ServingFrontEnd

    engine = serving.ContinuousBatcher(cfg, params, num_slots=2,
                                       max_decode_len=64)
    front = ServingFrontEnd(engine, port=0).start()
    try:
        return {spec["request_id"]:
                [int(t) for t in _post_json(front.url, spec)["tokens"]]
                for spec in specs}
    finally:
        front.shutdown()


def _drill_requests(seed: int, count: int,
                    max_new_tokens: int) -> list[dict]:
    """Deterministic per-seed request set (prompts drawn from a
    seed-keyed RNG, like ChaosPlan draws its schedule)."""
    rng = random.Random(seed * 7919 + 11)
    return [{"request_id": f"serve-drill-{seed}-{i}",
             "prompt": [rng.randrange(1, 96)
                        for _ in range(rng.randrange(2, 6))],
             "max_new_tokens": max_new_tokens}
            for i in range(count)]


def _post_json(url: str, payload: dict, timeout: float = 120) -> dict:
    req = urllib.request.Request(
        f"{url}/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _safe_json(body: bytes) -> dict:
    try:
        out = json.loads(body)
        return out if isinstance(out, dict) else {"raw": out}
    except ValueError:
        return {"raw": body.decode(errors="replace")}


def _request_raw(url: str, method: str = "GET",
                 payload: Optional[dict] = None,
                 timeout: float = 30) -> tuple[int, dict, dict]:
    """(status, json body, headers) without raising on HTTP errors —
    the drain-ladder assertions need the 503s' markers and
    Retry-After headers, not exceptions."""
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, _safe_json(resp.read()), \
                dict(resp.headers)
    except urllib.error.HTTPError as exc:
        return exc.code, _safe_json(exc.read()), dict(exc.headers)


def _await(cond, timeout: float, what: str) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


class _StreamClient(threading.Thread):
    """One streaming request through the router: collects every token
    line and the final result — the exactly-once evidence is exactly
    what this client observed on the wire."""

    def __init__(self, url: str, spec: dict) -> None:
        super().__init__(
            daemon=True, name=f"drill-client-{spec['request_id']}")
        self.url = url
        self.spec = dict(spec, stream=True)
        self.token_events: list[dict] = []
        self.final: Optional[dict] = None
        self.error: Optional[str] = None
        self.duplicates = 0

    def run(self) -> None:
        try:
            self._read(self.url, self.spec)
        except Exception as exc:  # noqa: BLE001 - recorded, asserted
            self.error = f"{type(exc).__name__}: {exc}"

    def _read(self, url: str, spec: dict) -> None:
        req = urllib.request.Request(
            f"{url}/v1/generate", data=json.dumps(spec).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            for line in resp:
                if not line.strip():
                    continue
                self._handle(json.loads(line))

    def _handle(self, event: dict) -> None:
        if "token" in event and "index" in event:
            idx = int(event["index"])
            if any(int(e["index"]) == idx
                   for e in self.token_events):
                self.duplicates += 1
            self.token_events.append(event)
        elif "tokens" in event:
            self.final = event
        elif event.get("error"):
            self.error = str(event["error"])

    def tokens(self) -> list[int]:
        return [int(e["token"]) for e in self.token_events]


class _RecoveringClient(_StreamClient):
    """The router-crash client protocol (docs/37): when the stream
    dies without a final line, cancel the request through the
    SUCCESSOR router (the dead router's relay may have left the run
    live on a replica), then re-submit with ``resume_tokens`` set to
    the journaled progress. The replica's duplicate gate (400 while
    the old run is still winding down, CompletedReplay if it already
    finished) is what keeps delivery exactly-once."""

    def __init__(self, url: str, spec: dict) -> None:
        super().__init__(url, spec)
        self.successor_url: Optional[str] = None
        self.successor_ready = threading.Event()
        self.resumed = False
        self.resume_from = 0  # journaled tokens at resume time
        self.broke_wall: Optional[float] = None
        self.recovered_window: Optional[tuple[float, float]] = None
        self._resume_reading = False

    def run(self) -> None:
        try:
            self._read(self.url, self.spec)
        except (OSError, http.client.HTTPException,
                urllib.error.URLError):
            pass  # the router died under us — recover below
        if self.final is not None or self.error is not None:
            return
        self.broke_wall = time.time()
        if not self.successor_ready.wait(timeout=60):
            self.error = "no successor router appeared"
            return
        try:
            self._resume()
        except Exception as exc:  # noqa: BLE001 - recorded, asserted
            self.error = (f"resume failed: "
                          f"{type(exc).__name__}: {exc}")

    def _handle(self, event: dict) -> None:
        super()._handle(event)
        if self._resume_reading and self.recovered_window is None:
            self.recovered_window = (self.broke_wall, time.time())

    def _resume(self) -> None:
        request_id = self.spec["request_id"]
        # Cancel-then-resume step 1: free the id fleet-wide. 404 just
        # means no replica owns a live run (it finished — the replay
        # cache will serve the resume).
        _request_raw(
            f"{self.successor_url}/v1/requests/{request_id}",
            method="DELETE")
        spec = dict(self.spec, resume_tokens=self.tokens())
        self.resumed = True
        self.resume_from = len(spec["resume_tokens"])
        self._resume_reading = True
        deadline = time.monotonic() + 60
        while True:
            try:
                self._read(self.successor_url, spec)
                return
            except urllib.error.HTTPError as exc:
                body = exc.read()
                # The cancel is asynchronous on the replica's engine
                # thread: "in flight" 400s just mean not-yet — retry.
                if exc.code == 400 and b"in flight" in body and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                    continue
                raise


def _check_stream(client: _StreamClient, reference: dict) -> None:
    request_id = client.spec["request_id"]
    assert client.error is None, f"{request_id}: {client.error}"
    assert client.final is not None, (
        f"{request_id}: stream ended without a final result line")
    assert client.duplicates == 0, (
        f"{request_id}: {client.duplicates} duplicate token indexes "
        f"reached the client (exactly-once broke)")
    indexes = [int(e["index"]) for e in client.token_events]
    assert indexes == list(range(len(indexes))), (
        f"{request_id}: token indexes not contiguous-from-zero: "
        f"{indexes}")
    tokens = client.tokens()
    assert tokens == [int(t) for t in client.final["tokens"]], (
        f"{request_id}: streamed tokens disagree with the final "
        f"result line")
    assert tokens == reference[request_id], (
        f"{request_id}: tokens diverged from the clean greedy "
        f"decode: {tokens} != {reference[request_id]}")


def _fleet_completed(fronts) -> int:
    return sum(f.stats()["completed_requests"] for f in fronts)


def _recovery_windows(recovery_log: list[dict]) -> list[dict]:
    return [{"start": e["at"] - e["recovery_seconds"], "end": e["at"],
             "request_id": e.get("request_id"),
             "resumed_tokens": e.get("resumed_tokens", 0)}
            for e in recovery_log
            if e.get("recovery_seconds", 0) > 0]


def _goodput_proof(report: dict, invariants: dict,
                   started_wall: float, ended_wall: float,
                   windows: list[dict]) -> None:
    """Price the drill like production would: the drill window is
    productive serving time, each measured recovery is a
    ``serving_recovery`` badput interval — the leg must be populated
    and the partition must stay exact."""
    store = MemoryStateStore()
    gp_events.emit(store, POOL_ID, gp_events.PROGRAM_STEP_WINDOW,
                   job_id="serving", task_id="drill",
                   start=started_wall, end=ended_wall,
                   attrs={"steps": len(windows) + 1})
    for window in windows:
        gp_events.emit(
            store, POOL_ID, gp_events.SERVE_RECOVERY,
            job_id="serving",
            task_id=window.get("request_id") or "drill",
            start=max(window["start"], started_wall),
            end=min(window["end"], ended_wall),
            attrs={"resumed_tokens": window.get("resumed_tokens", 0)})
    pool_report = _assert_partition_exact(store, POOL_ID, invariants)
    leg = pool_report["badput_seconds"].get("serving_recovery", 0.0)
    invariants["serving_recovery_seconds"] = leg
    assert leg > 0.0, (
        f"serving_recovery leg not populated: "
        f"{pool_report['badput_seconds']}")
    report["goodput"] = {
        "goodput_ratio": pool_report["goodput_ratio"],
        "badput_seconds": pool_report["badput_seconds"],
    }


def _pin_at(plan: ChaosPlan, lo: float = 0.05,
            hi: float = 0.25, **params) -> ChaosPlan:
    """Deterministic sequencing, like the batch drills: the fault
    must land with streams mid-decode. The drills gate on observed
    tokens (every stream >= 2) before honouring the offset, so the
    offset only needs to be a small floor past the gate — clamp it
    well under the throttled decode's runway (~0.8s for the default
    28 tokens at 0.03s/step), or a warm jit cache lets streams
    finish before the fault lands and the drill turns vacuous. Pins
    any drill-argument params too; still a pure function of the
    seed + arguments."""
    return dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(
            inj, at=min(max(inj.at, lo), hi),
            params=tuple(sorted(
                {**dict(inj.params), **params}.items())))
        for inj in plan.injections))


def _shutdown_all(*servers) -> None:
    for server in servers:
        if server is None:
            continue
        try:
            server.shutdown()
        except Exception:  # noqa: BLE001 - already-killed servers
            pass


# ------------------------------- drills --------------------------------

def run_replica_kill_drill(seed: int = 0, num_requests: int = 4,
                           max_new_tokens: int = 28,
                           step_delay: float = 0.03,
                           wait_timeout: float = 120.0) -> dict:
    """Replica-kill drill: a serving replica dies SIGKILL-style
    mid-decode (sockets severed, no drain, no final lines) under
    live streams. The router must detect the dead streams (bare EOF
    without a final line), resume each on the sibling via
    ``resume_tokens``, and keep every client's token stream
    exactly-once and byte-identical to a clean decode."""
    from batch_shipyard_tpu.models.router import ServingRouter

    plan = _pin_at(ChaosPlan.generate(
        seed, duration=4.0, num_nodes=2, kinds=("replica_kill",)))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    invariants = report["invariants"]
    specs = _drill_requests(seed, num_requests, max_new_tokens)
    cfg, params, fronts = _build_fleet(2, step_delay)
    router = None
    started_wall = time.time()
    try:
        reference = _reference_outputs(cfg, params, specs)
        router = ServingRouter(
            [f.url for f in fronts], health_interval=0.1,
            retry_backoff_base=0.02).start()
        clients = [_StreamClient(router.url, spec) for spec in specs]
        started = time.monotonic()
        for client in clients:
            client.start()
        injection = plan.injections[0]
        _await(lambda: all(len(c.token_events) >= 2
                           for c in clients),
               wait_timeout, "every stream mid-decode")
        delay = started + injection.at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        victim = fronts[injection.node_index % len(fronts)]
        victim.kill()
        report["applied"].append(dict(
            injection.to_dict(), victim=victim.url,
            applied_at=round(time.monotonic() - started, 3)))
        for client in clients:
            client.join(wait_timeout)
        assert not any(c.is_alive() for c in clients), (
            "stream clients hung past the drill window")
        for client in clients:
            _check_stream(client, reference)
        stats = router.stats()
        invariants["completed_streams"] = num_requests
        invariants["lost_streams"] = stats["lost_streams"]
        assert stats["lost_streams"] == 0, (
            f"lost streams: {stats['lost_streams']}")
        invariants["recoveries"] = stats["recoveries"]
        assert stats["recoveries"] >= 1, (
            "the kill never interrupted a stream (vacuous drill)")
        for entry in stats["recovery_log"]:
            if not entry.get("synthesized"):
                assert 0 < entry["resumed_tokens"] < max_new_tokens, (
                    f"recovery was not mid-stream: {entry}")
        completed = _fleet_completed(fronts)
        invariants["fleet_completed_requests"] = completed
        assert completed == num_requests, (
            f"exactly-once decode broke: {completed} completions "
            f"for {num_requests} requests")
        _goodput_proof(report, invariants, started_wall, time.time(),
                       _recovery_windows(stats["recovery_log"]))
        invariants["ok"] = True
    finally:
        _shutdown_all(router, *fronts)
    return report


def run_replica_drain_drill(seed: int = 0, num_requests: int = 4,
                            max_new_tokens: int = 28,
                            step_delay: float = 0.03,
                            grace: float = 0.25,
                            wait_timeout: float = 120.0) -> dict:
    """Replica-drain drill: a preempt notice (the agent's cooperative
    channel, agent/preemption.py) lands on a replica under live
    streams. The full drain ladder must fire: healthz flips to
    503+draining (the router pulls it from rotation as COOPERATIVE,
    not a fault), direct admissions get 503+Retry-After with the
    draining marker, new routed requests land on the sibling, and
    decodes still active at the grace deadline are abandoned with a
    draining marker the router resumes from — zero lost requests,
    byte-identical streams."""
    from batch_shipyard_tpu.agent import preemption
    from batch_shipyard_tpu.models.router import ServingRouter

    plan = _pin_at(ChaosPlan.generate(
        seed, duration=4.0, num_nodes=2,
        kinds=("replica_drain_notice",)), grace=grace)
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    invariants = report["invariants"]
    specs = _drill_requests(seed, num_requests, max_new_tokens)
    cfg, params, fronts = _build_fleet(2, step_delay)
    router = None
    started_wall = time.time()
    notice_path = os.path.join(
        tempfile.mkdtemp(prefix="shipyard-serve-drill-"),
        "preempt-request.json")
    try:
        reference = _reference_outputs(cfg, params, specs)
        injection = plan.injections[0]
        victim = fronts[injection.node_index % len(fronts)]
        survivor = fronts[1 - fronts.index(victim)]
        assert victim.arm_preempt_drain(
            path=notice_path, grace_s=injection.param("grace"),
            poll_interval=0.05), "preempt watcher failed to arm"
        router = ServingRouter(
            [f.url for f in fronts], health_interval=0.1,
            retry_backoff_base=0.02).start()
        clients = [_StreamClient(router.url, spec) for spec in specs]
        started = time.monotonic()
        for client in clients:
            client.start()
        _await(lambda: all(len(c.token_events) >= 2
                           for c in clients),
               wait_timeout, "every stream mid-decode")
        delay = started + injection.at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        preemption.write_request(notice_path,
                                 reason="serving drain drill")
        report["applied"].append(dict(
            injection.to_dict(), victim=victim.url,
            applied_at=round(time.monotonic() - started, 3)))
        _await(lambda: victim.draining, 10.0,
               "the preempt notice to flip the replica draining")
        # The drain ladder, rung by rung. healthz:
        code, payload, _ = _request_raw(f"{victim.url}/healthz")
        assert code == 503 and payload.get("draining"), (
            f"draining healthz: {code} {payload}")
        # direct admission:
        code, payload, headers = _request_raw(
            f"{victim.url}/v1/generate", method="POST",
            payload={"prompt": [2, 7], "max_new_tokens": 2})
        assert code == 503 and payload.get("draining"), (
            f"draining admission: {code} {payload}")
        assert headers.get("Retry-After"), (
            "draining 503 without Retry-After")
        # router rotation:
        _await(lambda: any(s["draining"]
                           for s in router.replicas()),
               10.0, "the router to observe the drain")
        probe = _post_json(router.url, {
            "request_id": f"serve-drill-{seed}-probe",
            "prompt": [3, 1, 4], "max_new_tokens": 2})
        assert probe["_replica"] == survivor.url, (
            f"routed to the draining replica: {probe['_replica']}")
        for client in clients:
            client.join(wait_timeout)
        assert not any(c.is_alive() for c in clients), (
            "stream clients hung past the drill window")
        for client in clients:
            _check_stream(client, reference)
        stats = router.stats()
        invariants["completed_streams"] = num_requests
        invariants["lost_streams"] = stats["lost_streams"]
        assert stats["lost_streams"] == 0, (
            f"lost streams: {stats['lost_streams']}")
        invariants["recoveries"] = stats["recoveries"]
        assert stats["recoveries"] >= 1, (
            "no decode was drain-abandoned (vacuous drill: raise "
            "max_new_tokens or lower grace)")
        snapshots = {s["url"]: s for s in router.replicas()}
        invariants["victim_unhealthy_total"] = \
            snapshots[victim.url]["unhealthy_total"]
        assert snapshots[victim.url]["unhealthy_total"] == 0, (
            "cooperative drain was counted as a fault")
        invariants["drain_rejections"] = \
            victim.stats()["drain_rejections"]
        assert invariants["drain_rejections"] >= 1
        completed = _fleet_completed(fronts)
        invariants["fleet_completed_requests"] = completed
        assert completed == num_requests + 1, (  # +1: the probe
            f"exactly-once decode broke: {completed} completions "
            f"for {num_requests + 1} requests")
        _goodput_proof(report, invariants, started_wall, time.time(),
                       _recovery_windows(stats["recovery_log"]))
        invariants["ok"] = True
    finally:
        _shutdown_all(router, *fronts)
    return report


def run_router_restart_drill(seed: int = 0, num_requests: int = 4,
                             max_new_tokens: int = 28,
                             step_delay: float = 0.03,
                             wait_timeout: float = 120.0) -> dict:
    """Router-restart drill: the serving ROUTER process crashes
    mid-stream (every client connection severed) and a successor
    router takes over the same replica fleet after a short downtime.
    Clients run the documented cancel-then-resume protocol against
    the successor; the REPLICAS' duplicate gates (in-flight 400s,
    the completed-replay cache) — not any router state — must keep
    delivery exactly-once and byte-identical."""
    from batch_shipyard_tpu.models.router import ServingRouter

    plan = _pin_at(ChaosPlan.generate(
        seed, duration=4.0, num_nodes=2, kinds=("router_restart",)))
    plan = dataclasses.replace(plan, injections=tuple(
        dataclasses.replace(inj, params=tuple(sorted(
            {**dict(inj.params),
             "downtime": min(max(inj.param("downtime", 0.2), 0.1),
                             0.3)}.items())))
        for inj in plan.injections))
    report: dict = {"seed": plan.seed,
                    "fingerprint": plan.fingerprint(),
                    "plan": plan.to_dict(),
                    "applied": [], "invariants": {}}
    invariants = report["invariants"]
    specs = _drill_requests(seed, num_requests, max_new_tokens)
    cfg, params, fronts = _build_fleet(2, step_delay)
    router = successor = None
    started_wall = time.time()
    try:
        reference = _reference_outputs(cfg, params, specs)
        urls = [f.url for f in fronts]
        router = ServingRouter(urls, health_interval=0.1,
                               retry_backoff_base=0.02).start()
        clients = [_RecoveringClient(router.url, spec)
                   for spec in specs]
        started = time.monotonic()
        for client in clients:
            client.start()
        injection = plan.injections[0]
        _await(lambda: all(len(c.token_events) >= 2
                           for c in clients),
               wait_timeout, "every stream mid-decode")
        delay = started + injection.at - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        router.kill()
        report["applied"].append(dict(
            injection.to_dict(),
            applied_at=round(time.monotonic() - started, 3)))
        time.sleep(injection.param("downtime", 0.2))
        successor = ServingRouter(urls, health_interval=0.1,
                                  retry_backoff_base=0.02).start()
        for client in clients:
            client.successor_url = successor.url
            client.successor_ready.set()
        for client in clients:
            client.join(wait_timeout)
        assert not any(c.is_alive() for c in clients), (
            "stream clients hung past the drill window")
        for client in clients:
            _check_stream(client, reference)
        resumed = sum(1 for c in clients if c.resumed)
        invariants["completed_streams"] = num_requests
        invariants["resumed_clients"] = resumed
        assert resumed >= 1, (
            "the crash never interrupted a stream (vacuous drill)")
        completed = _fleet_completed(fronts)
        invariants["fleet_completed_requests"] = completed
        assert completed == num_requests, (
            f"exactly-once decode broke: {completed} completions "
            f"for {num_requests} requests — a request decoded to "
            f"completion twice across the router handoff")
        windows = [
            {"start": c.recovered_window[0],
             "end": c.recovered_window[1],
             "request_id": c.spec["request_id"],
             "resumed_tokens": c.resume_from}
            for c in clients
            if c.resumed and c.recovered_window is not None and
            c.recovered_window[1] > c.recovered_window[0]]
        invariants["recovery_windows"] = len(windows)
        _goodput_proof(report, invariants, started_wall, time.time(),
                       windows)
        invariants["ok"] = True
    finally:
        _shutdown_all(router, successor, *fronts)
    return report

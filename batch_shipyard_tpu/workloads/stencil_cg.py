"""Sparse iterative-solver benchmark: the HPCG recipe analog
(/root/reference/recipes/HPCG-Infiniband-IntelMPI — conjugate gradient
on a 27-point stencil, reporting memory-bound GFLOP/s).

TPU restatement: CG on the 3D 7-point Laplacian expressed as jnp.roll
stencil applications over a dense [n,n,n] grid — no sparse matrix, so
XLA fuses the matvec into a handful of HBM-bandwidth-bound elementwise
passes (the regime HPCG measures). The iteration is one lax.scan; the
convergence check happens after, on the recorded residual history
(no data-dependent control flow under jit).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.workloads import distributed


def laplacian_3d(x):
    """7-point stencil with zero (Dirichlet) boundaries via rolls +
    boundary masking."""
    total = jnp.zeros_like(x)
    for axis in range(3):
        for shift in (1, -1):
            rolled = jnp.roll(x, shift, axis=axis)
            # Zero the wrapped-around plane (Dirichlet boundary).
            n = x.shape[axis]
            idx = 0 if shift == 1 else n - 1
            rolled = jax.lax.dynamic_update_slice_in_dim(
                rolled, jnp.zeros_like(
                    jax.lax.dynamic_slice_in_dim(rolled, idx, 1,
                                                 axis=axis)),
                idx, axis=axis)
            total = total + rolled
    return 6.0 * x - total


def cg_solve(b, iters: int):
    """iters CG iterations; returns (x, residual-norm history)."""

    def step(carry, _):
        x, r, p, rs = carry
        ap = laplacian_3d(p)
        alpha = rs / jnp.vdot(p, ap)
        x = x + alpha * p
        r = r - alpha * ap
        rs_new = jnp.vdot(r, r)
        p = r + (rs_new / rs) * p
        return (x, r, p, rs_new), jnp.sqrt(rs_new)

    x0 = jnp.zeros_like(b)
    r0 = b
    rs0 = jnp.vdot(r0, r0)
    (x, _, _, _), history = jax.lax.scan(
        step, (x0, r0, r0, rs0), None, length=iters)
    return x, history


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=256,
                        help="grid side (n^3 unknowns)")
    parser.add_argument("--cg-iters", type=int, default=50)
    parser.add_argument("--reps", type=int, default=3)
    args = parser.parse_args()
    ctx = distributed.setup()
    rng = np.random.RandomState(0)
    b = jnp.asarray(rng.randn(args.n, args.n, args.n), jnp.float32)
    solver = jax.jit(lambda b: cg_solve(b, args.cg_iters))
    x, history = solver(b)
    x.block_until_ready()
    start = time.perf_counter()
    for _ in range(args.reps):
        x, history = solver(b)
    x.block_until_ready()
    elapsed = (time.perf_counter() - start) / args.reps
    # Per CG iteration: stencil matvec (~8 flops/pt) + 2 dots +
    # 3 axpys (~10 flops/pt) — the HPCG bookkeeping.
    flops_per_iter = 18.0 * args.n ** 3
    gflops = args.cg_iters * flops_per_iter / elapsed / 1e9
    # Benchmark-style validation (HPCG runs fixed iterations and
    # reports the residual): finite and meaningfully reduced. Full
    # convergence at n=256 needs O(n) iterations — condition number
    # grows as (n/pi)^2 — which is not what's being measured here.
    hist = np.asarray(history)
    converged = bool(np.all(np.isfinite(hist)) and
                     hist[-1] < hist[0] * 0.5)
    distributed.log(ctx, (
        f"stencil_cg: n={args.n}^3 {gflops:.1f} GFLOP/s "
        f"(memory-bound), residual {hist[0]:.2e} -> {hist[-1]:.2e} "
        f"in {args.cg_iters} iters "
        f"{'PASS' if converged else 'FAIL'}"))
    return 0 if converged else 1


if __name__ == "__main__":
    raise SystemExit(main())

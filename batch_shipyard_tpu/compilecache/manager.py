"""Persistent-compile-cache manager: knobs, identity key, hit stats.

JAX's persistent compilation cache keys entries by the full XLA
computation + backend fingerprint, so a stale or foreign entry can
never produce a wrong executable — it just misses. The manager adds
the operational layer the cache itself doesn't have:

  * ``enable`` points ``jax_compilation_cache_dir`` at a directory
    (with the min-entry-size / min-compile-time thresholds dropped to
    zero so even fast CPU-test compiles land) and stamps the dir with
    a sidecar ``identity.json``.
  * ``identity_key`` is the *transport* key for pool-wide seeding
    (compilecache/seeding.py): jax/jaxlib versions, device kind,
    topology, and an optional model-config digest. Shipping a cache
    tar whose identity mismatches the node would waste bytes on
    entries that can only miss, so seeding refuses them.
  * ``track`` measures one compile region by diffing cache-dir
    contents around it: new entries mean a cold compile (its wall time
    is remembered in a ``cache_meta.json`` sidecar, which travels with
    the seeded tar); no new entries over a non-empty cache means a
    warm hit, and ``saved_seconds`` is the remembered cold time minus
    the measured warm time. These land in the goodput compile events'
    attrs (``cache_hit`` / ``saved_seconds``) so accounting can report
    ``compile_saved_seconds`` next to compile badput.

No module-level jax import: the node agent and the CLI import this for
env names and seeding validation without paying (or requiring) a JAX
backend.
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import os
import re
import time
from typing import Any, Iterator, Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

# Env var the node agent exports into every task: the node-local
# persistent cache directory (seeded from / exported to the pool's
# state store around tasks).
CACHE_DIR_ENV = "SHIPYARD_COMPILE_CACHE_DIR"

# Sidecar files the manager owns inside the cache dir. They are not
# cache entries (snapshot() excludes them) but they DO travel with the
# seeded tar: identity gates transport, meta carries cold times so a
# seeded node can price its warm hits.
IDENTITY_FILE = "identity.json"
META_FILE = "cache_meta.json"
_SIDECARS = (IDENTITY_FILE, META_FILE)

# Object repr memory addresses (``<function f at 0x7f...>``) must
# never leak into a digest: they vary per process, and the whole point
# of the identity key is cross-process stability.
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def _stable(obj: Any) -> Any:
    """Reduce an arbitrary config value to a deterministic,
    process-independent structure for digesting."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _stable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {str(k): _stable(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_stable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if callable(obj):
        return getattr(obj, "__qualname__", type(obj).__name__)
    return _ADDR_RE.sub("0x", str(obj))


def config_digest(obj: Any) -> str:
    """Stable short digest of a model/config object (dataclass, dict,
    anything): identical configs digest identically across processes;
    any field change changes it."""
    payload = json.dumps(_stable(obj), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def identity_key(*, jax_version: Optional[str] = None,
                 jaxlib_version: Optional[str] = None,
                 backend: Optional[str] = None,
                 device_kind: Optional[str] = None,
                 device_count: Optional[int] = None,
                 process_count: Optional[int] = None,
                 mesh_shape: Optional[dict] = None,
                 model_digest: Optional[str] = None) -> str:
    """The cache transport identity: pure over its inputs (tests pass
    them explicitly); unspecified fields resolve from the live JAX
    runtime. Two nodes share cache artifacts iff their keys match."""
    if (jax_version is None or jaxlib_version is None or
            backend is None or device_kind is None or
            device_count is None or process_count is None):
        import jax
        import jaxlib
        jax_version = jax_version or jax.__version__
        jaxlib_version = jaxlib_version or jaxlib.__version__
        backend = backend or jax.default_backend()
        devices = jax.devices()
        device_kind = device_kind or devices[0].device_kind
        device_count = (len(devices) if device_count is None
                        else device_count)
        process_count = (jax.process_count() if process_count is None
                         else process_count)
    payload = json.dumps({
        "jax": jax_version, "jaxlib": jaxlib_version,
        "backend": backend, "device_kind": device_kind,
        "device_count": int(device_count),
        "process_count": int(process_count),
        "mesh_shape": _stable(mesh_shape or {}),
        "model_digest": model_digest or "",
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def snapshot(cache_dir: str) -> dict[str, int]:
    """Cache ENTRIES (name -> size): everything in the dir except the
    manager sidecars and XLA's ``-atime`` access markers."""
    entries: dict[str, int] = {}
    try:
        for name in os.listdir(cache_dir):
            # Excluded: manager sidecars, XLA access-time markers,
            # and in-flight atomic-write temporaries (seeding).
            if name in _SIDECARS or name.endswith(
                    ("-atime", ".tmp", ".seedtmp")):
                continue
            path = os.path.join(cache_dir, name)
            if os.path.isfile(path):
                entries[name] = os.path.getsize(path)
    except OSError:
        pass
    return entries


class CompileCacheManager:
    """One process's handle on an enabled persistent cache dir."""

    def __init__(self, cache_dir: str, identity: str) -> None:
        self.cache_dir = os.path.abspath(cache_dir)
        self.identity = identity
        self.hits = 0
        self.misses = 0
        self.saved_seconds = 0.0
        # Labels already measured IN THIS PROCESS: a repeat (e.g.
        # replica engines 2..N sharing replica 1's module-level jits)
        # reuses the in-process dispatch cache, not the persistent
        # cache — crediting it as a warm hit would multiply
        # compile_saved_seconds by the replica count.
        self._seen_labels: set = set()

    # ------------------------------ stats ------------------------------

    def entries(self) -> dict[str, int]:
        return snapshot(self.cache_dir)

    def stats(self) -> dict:
        entries = self.entries()
        return {
            "cache_dir": self.cache_dir, "identity": self.identity,
            "entries": len(entries),
            "bytes": sum(entries.values()),
            "hits": self.hits, "misses": self.misses,
            "saved_seconds": round(self.saved_seconds, 6),
        }

    def _load_meta(self) -> dict:
        try:
            with open(os.path.join(self.cache_dir, META_FILE),
                      encoding="utf-8") as fh:
                meta = json.load(fh)
            return meta if isinstance(meta, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save_meta(self, meta: dict) -> None:
        path = os.path.join(self.cache_dir, META_FILE)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(meta, fh)
            os.replace(tmp, path)
        except OSError:
            logger.debug("cache meta write failed", exc_info=True)

    @contextlib.contextmanager
    def track(self, label: str) -> Iterator[dict]:
        """Measure one compile region against the cache dir. Yields a
        result dict filled in on exit: ``cache_hit``, ``new_entries``,
        ``elapsed_seconds``, ``saved_seconds``. A cold compile records
        its wall time per label in the meta sidecar; a later warm run
        of the same label in a FRESH process (this node or a seeded
        one) prices its saving against that. A repeat of a label
        within one process is in-process jit reuse, not a persistent
        cache hit — it is reported (``in_process_reuse``) but neither
        counted nor priced."""
        first_of_label = label not in self._seen_labels
        self._seen_labels.add(label)
        before = snapshot(self.cache_dir)
        start = time.perf_counter()
        result: dict = {"label": label}
        try:
            yield result
        finally:
            elapsed = time.perf_counter() - start
            after = snapshot(self.cache_dir)
            new = [name for name in after if name not in before]
            hit = not new and bool(before) and first_of_label
            result["elapsed_seconds"] = elapsed
            result["new_entries"] = len(new)
            result["cache_hit"] = bool(hit)
            result["in_process_reuse"] = not first_of_label and \
                not new
            saved = 0.0
            if not result["in_process_reuse"]:
                meta = self._load_meta()
                if new:
                    # Cold: remember this label's full compile cost
                    # so a warm replay (here or on a seeded node) can
                    # price the time it did NOT spend. First cold
                    # measurement wins: a PARTIALLY warm rerun (one
                    # changed function over a seeded cache) also
                    # lands here, and letting its mostly-warm elapsed
                    # overwrite the true cold time would corrupt
                    # every later node's saved_seconds (the meta
                    # travels with the seed tar).
                    meta.setdefault("cold_seconds",
                                    {}).setdefault(label, elapsed)
                    self._save_meta(meta)
                    self.misses += 1
                elif hit:
                    cold = meta.get("cold_seconds", {}).get(label)
                    try:
                        saved = max(0.0, float(cold) - elapsed)
                    except (TypeError, ValueError):
                        saved = 0.0
                    self.hits += 1
                else:
                    self.misses += 1
            result["saved_seconds"] = saved
            self.saved_seconds += saved


_current: Optional[CompileCacheManager] = None


def current() -> Optional[CompileCacheManager]:
    """The process's enabled manager, or None (cache disabled)."""
    return _current


def identity_subdir(cache_root: str, identity: str) -> str:
    """The identity-namespaced cache dir under a shared root."""
    return os.path.join(os.path.abspath(cache_root),
                        f"ident-{identity}")


def list_identity_dirs(cache_root: str) -> dict[str, str]:
    """identity -> subdir for every namespaced cache under a root."""
    out: dict[str, str] = {}
    try:
        for name in os.listdir(cache_root):
            if not name.startswith("ident-"):
                continue
            path = os.path.join(cache_root, name)
            if os.path.isdir(path):
                out[name[len("ident-"):]] = path
    except OSError:
        pass
    return out


def enable(cache_root: str, *,
           min_entry_size_bytes: int = 0,
           min_compile_time_secs: float = 0.0,
           identity: Optional[str] = None,
           mesh_shape: Optional[dict] = None,
           model_digest: Optional[str] = None,
           configure_jax: bool = True) -> CompileCacheManager:
    """Point the persistent XLA compilation cache at ``cache_root``'s
    identity-namespaced subdir and install the process-global manager.
    Idempotent. Namespacing is what lets MIXED pools share one node
    dir: a transformer task and a resnet task (different identities)
    each warm their own subdir instead of clobbering each other's —
    XLA entries are self-keying, but cold-time metas and export
    artifacts are not. ``configure_jax=False`` skips the jax.config
    writes (tests and agent-side tooling that never compile)."""
    global _current
    if identity is None:
        identity = identity_key(mesh_shape=mesh_shape,
                                model_digest=model_digest)
    cache_dir = identity_subdir(cache_root, identity)
    os.makedirs(cache_dir, exist_ok=True)
    if read_identity(cache_dir) != identity:
        try:
            with open(os.path.join(cache_dir, IDENTITY_FILE), "w",
                      encoding="utf-8") as fh:
                json.dump({"identity": identity,
                           "written_at": util.datetime_utcnow_iso()},
                          fh)
        except OSError:
            logger.debug("identity write failed", exc_info=True)
    if configure_jax:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          int(min_entry_size_bytes))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_secs))
        try:
            # Any compile that ran BEFORE enable latches the cache
            # module to its initialized-disabled state for the process
            # (config updates alone don't un-latch it); reset so the
            # new dir takes effect even mid-process.
            from jax.experimental.compilation_cache import (
                compilation_cache as jax_cc)
            jax_cc.reset_cache()
        except Exception:  # noqa: BLE001 - experimental jax API
            logger.debug("compilation cache reset unavailable",
                         exc_info=True)
    _current = CompileCacheManager(cache_dir, identity)
    return _current


def read_identity(cache_dir: str) -> Optional[str]:
    """The identity a cache dir was stamped with, or None."""
    try:
        with open(os.path.join(cache_dir, IDENTITY_FILE),
                  encoding="utf-8") as fh:
            value = json.load(fh).get("identity")
        return value if isinstance(value, str) else None
    except (OSError, ValueError):
        return None


@contextlib.contextmanager
def tracked(attrs: dict, label: str) -> Iterator[None]:
    """Nest inside a goodput compile/warm-up phase to stamp the
    event's attrs with ``cache_hit`` / ``saved_seconds``::

        with goodput_events.phase(PROGRAM_COMPILE, what="x") as attrs,\\
                compilecache.tracked(attrs, "x"):
            ...  # the compile

    No-op when no manager is enabled."""
    mgr = current()
    if mgr is None:
        yield
        return
    with mgr.track(label) as result:
        yield
    if result.get("in_process_reuse"):
        # Replica N reusing replica 1's in-process jits is neither a
        # persistent-cache hit nor a miss — stamping either would
        # skew the pool's hit/saved accounting.
        return
    attrs["cache_hit"] = result["cache_hit"]
    attrs["saved_seconds"] = round(result["saved_seconds"], 6)


def add_compile_cache_args(parser) -> None:
    """The shared warm-start flag surface of every train/serve
    workload (the checkpoint.add_checkpoint_args pattern)."""
    group = parser.add_argument_group("compile cache")
    group.add_argument(
        "--compile-cache-dir",
        default=os.environ.get(CACHE_DIR_ENV) or None,
        help="persistent XLA compilation cache dir (default: "
             f"${CACHE_DIR_ENV}, which the node agent exports on "
             "pools; unset = cold compiles)")
    group.add_argument(
        "--no-compile-cache", action="store_true",
        help="opt out of the persistent compile cache even when "
             f"${CACHE_DIR_ENV} is set")
    group.add_argument(
        "--aot-precompile", action="store_true",
        help="AOT lower+compile the hot functions against abstract "
             "shapes so compilation overlaps data/loader startup "
             "instead of blocking the first step")


def enable_from_args(args, *, mesh_shape: Optional[dict] = None,
                     model_digest: Optional[str] = None
                     ) -> Optional[CompileCacheManager]:
    """The workload-side enable hook (the AST check in
    tests/test_names_consistency.py requires every parallel.train
    workload to call this): enables the persistent cache when a dir is
    configured, returns None when disabled. Never raises — a broken
    cache dir must not fail the work it would have sped up."""
    cache_dir = getattr(args, "compile_cache_dir", None)
    if not cache_dir or getattr(args, "no_compile_cache", False):
        return None
    try:
        return enable(cache_dir, mesh_shape=mesh_shape,
                      model_digest=model_digest)
    except Exception:  # noqa: BLE001 - warm start is best-effort
        logger.warning("compile cache enable failed for %s",
                       cache_dir, exc_info=True)
        return None

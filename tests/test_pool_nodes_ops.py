"""pool nodes operator verbs (VERDICT r4 next #2) on the FakePod and
localhost substrates: count, grls, ps, zap, prune via agent control
messages; reboot/del via slice-granular substrate ops. Reference:
shipyard.py:1795-1945, convoy/fleet.py:2468, convoy/batch.py:3074."""

import time

import pytest

from batch_shipyard_tpu.agent import cascade
from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate

GLOBAL = settings_mod.global_settings({})


def make_env(slices=2):
    conf = {"pool_specification": {
        "id": "pool1", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-8",
                "num_slices": slices},
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool, GLOBAL, conf)
    return store, substrate, pool


@pytest.fixture()
def env():
    store, substrate, pool = make_env()
    yield store, substrate, pool
    substrate.stop_all()


def test_nodes_count_histogram(env):
    store, _substrate, pool = env
    counts = pool_mgr.node_counts(store, pool.id)
    # v5litepod-8 = 2 workers per slice, 2 slices.
    assert counts["total"] == 4
    assert sum(counts["by_state"].values()) == 4
    assert set(counts["by_state"]) <= {"idle", "running"}


def test_nodes_grls_all_and_single(env):
    store, substrate, pool = env
    rows = pool_mgr.remote_login_settings(store, substrate, pool.id)
    assert len(rows) == 4
    assert all(r["ip"] for r in rows)
    one = pool_mgr.remote_login_settings(
        store, substrate, pool.id, rows[0]["node_id"])
    assert len(one) == 1 and one[0]["node_id"] == rows[0]["node_id"]
    with pytest.raises(pool_mgr.PoolNotFoundError):
        pool_mgr.remote_login_settings(store, substrate, pool.id,
                                       "nope")


def test_nodes_ps_shows_running_task_and_zap_kills_it(env):
    store, _substrate, pool = env
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "job1", "tasks": [{"command": "sleep 60"}]}]}))
    # Wait for the task to actually start somewhere.
    deadline = time.monotonic() + 15
    busy = []
    while time.monotonic() < deadline and not busy:
        replies = pool_mgr.nodes_ps(store, pool.id, timeout=10)
        busy = [r for r in replies if r.get("running_tasks")]
        time.sleep(0.1)
    assert busy, f"no node reported the running task: {replies}"
    entry = busy[0]["running_tasks"][0]
    assert entry["job_id"] == "job1"
    assert entry["pid"]

    zapped = pool_mgr.nodes_zap(store, pool.id,
                                node_id=busy[0]["node_id"],
                                timeout=10)
    assert zapped[0]["killed_tasks"] == [
        {"job_id": "job1", "task_id": entry["task_id"]}]
    # The killed task completes as failed (nonzero exit).
    tasks = jobs_mgr.wait_for_tasks(store, pool.id, "job1",
                                    timeout=30)
    assert tasks[0]["state"] in ("failed", "completed")
    assert tasks[0]["exit_code"] != 0


def test_nodes_ps_idle_pool_is_empty(env):
    store, _substrate, pool = env
    replies = pool_mgr.nodes_ps(store, pool.id, timeout=10)
    assert len(replies) == 4
    assert all(r["running_tasks"] == [] for r in replies)
    assert all("replied_at" in r for r in replies)


def test_nodes_prune_removes_unreferenced_cache(env):
    store, substrate, pool = env
    # Preload two tarballs, then rewrite the manifest to reference
    # only one — prune must drop exactly the orphan.
    cascade.preload_image_tarball(store, pool.id, "img/keep:1",
                                  (b"x" * 1024 for _ in range(2)))
    cascade.preload_image_tarball(store, pool.id, "img/drop:1",
                                  (b"y" * 1024 for _ in range(2)))
    nodes = pool_mgr.list_nodes(store, pool.id)
    agent = substrate.agent(pool.id, nodes[0].node_id)
    prov = cascade.CascadeImageProvisioner(store)
    agent._image_provisioner = prov
    # Force both tarballs into this node's cache.
    prov(agent, ["img/keep:1", "img/drop:1"])
    import os
    cache = prov._cache_dir
    assert len(os.listdir(cache)) == 2
    # Orphan img/drop: remove its manifest row.
    from batch_shipyard_tpu.state import names as names_mod
    from batch_shipyard_tpu.utils import util as util_mod
    drop_key = util_mod.hash_string("docker:img/drop:1")[:24]
    store.delete_entity(names_mod.TABLE_IMAGES, pool.id, drop_key)

    reply = pool_mgr.nodes_prune(store, pool.id,
                                 node_id=nodes[0].node_id,
                                 timeout=10)[0]
    assert reply["removed_cached"] == [f"{drop_key}.tar"]
    assert reply["freed_bytes"] == 2048
    assert len(os.listdir(cache)) == 1


def test_reboot_node_recreates_its_slice(env):
    store, substrate, pool = env
    before = pool_mgr.list_nodes(store, pool.id)
    victim = [n for n in before if n.slice_index == 1][0]
    s = pool_mgr.reboot_node(store, substrate, pool, victim.node_id)
    assert s == 1
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        after = pool_mgr.list_nodes(store, pool.id)
        ready = [n for n in after
                 if n.state in pool_mgr.READY_STATES]
        if len(after) == 4 and len(ready) == 4:
            break
        time.sleep(0.1)
    assert len(pool_mgr.list_nodes(store, pool.id)) == 4


def test_delete_node_deallocates_slice_without_replacement(env):
    store, substrate, pool = env
    before = pool_mgr.list_nodes(store, pool.id)
    victim = [n for n in before if n.slice_index == 0][0]
    s = pool_mgr.delete_node(store, substrate, pool, victim.node_id)
    assert s == 0
    after = pool_mgr.list_nodes(store, pool.id)
    assert len(after) == 2
    assert all(n.slice_index == 1 for n in after)
    with pytest.raises(pool_mgr.PoolNotFoundError):
        pool_mgr.get_node(store, pool.id, victim.node_id)


def test_send_control_and_wait_times_out_on_dead_node(env):
    store, _substrate, pool = env
    with pytest.raises(TimeoutError):
        pool_mgr.send_control_and_wait(
            store, pool.id, "no-such-node", {"type": "ps"},
            timeout=1.0)


def test_fanout_reports_non_ready_nodes_without_waiting(env):
    store, _substrate, pool = env
    from batch_shipyard_tpu.state import names as names_mod
    store.upsert_entity(names_mod.TABLE_NODES, pool.id, "ghost", {
        "state": "suspended", "node_index": 99, "slice_index": 9,
        "worker_index": 0})
    start = time.monotonic()
    replies = pool_mgr.nodes_ps(store, pool.id, timeout=10)
    elapsed = time.monotonic() - start
    ghost = [r for r in replies if r.get("node_id") == "ghost"][0]
    assert "not ready" in ghost["error"]
    # The suspended node must not consume the timeout: live nodes
    # answer fast and the ghost is reported immediately.
    assert elapsed < 8
    assert sum(1 for r in replies if "error" not in r) == 4


def test_expired_destructive_control_is_dropped(env):
    store, substrate, pool = env
    node = pool_mgr.list_nodes(store, pool.id)[0]
    agent = substrate.agent(pool.id, node.node_id)
    from batch_shipyard_tpu.state import names as names_mod
    reply_key = names_mod.control_reply_key(pool.id, node.node_id,
                                            "deadbeef")
    agent._handle_control({"type": "zap", "reply_key": reply_key,
                           "expires_at": time.time() - 5.0})
    # Dropped: no reply object written, nothing executed.
    assert not store.object_exists(reply_key)
    # A live (unexpired) one still answers.
    agent._handle_control({"type": "zap", "reply_key": reply_key,
                           "expires_at": time.time() + 30.0})
    assert store.object_exists(reply_key)

"""Transformer LM training payload: long-context flagship recipe.

Supports dp/fsdp/sp/tp over the global device mesh; with --sp > 1 the
attention runs as ring attention over the ICI ring (exact, memory
O(T/sp) per device) — the long-context mechanism SURVEY.md section 5.7
calls net-new design space.

Usage (recipe command):
    python -m batch_shipyard_tpu.workloads.train_transformer \
        --seq-len 8192 --sp 4 --tp 2 --steps 20
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu import compilecache
from batch_shipyard_tpu.agent import preemption
from batch_shipyard_tpu.goodput import events as goodput_events
from batch_shipyard_tpu.parallel import mesh as mesh_mod
from batch_shipyard_tpu.parallel import train as train_mod
from batch_shipyard_tpu.workloads import checkpoint
from batch_shipyard_tpu.workloads import distributed


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--d-model", type=int, default=1024)
    parser.add_argument("--n-layers", type=int, default=12)
    parser.add_argument("--n-heads", type=int, default=16)
    parser.add_argument("--d-ff", type=int, default=2816)
    parser.add_argument("--vocab", type=int, default=32000)
    parser.add_argument("--seq-len", type=int, default=2048)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--warmup", type=int, default=2)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel axis (requires --moe-"
                             "experts divisible by ep)")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="replace every moe-every'th MLP with N "
                             "routed experts (0 = dense)")
    parser.add_argument("--moe-every", type=int, default=2)
    parser.add_argument("--int8", action="store_true",
                        help="int8 MXU matmuls for projections/MLP "
                             "(QAT straight-through backward)")
    parser.add_argument("--no-remat", action="store_true")
    checkpoint.add_checkpoint_args(parser)
    compilecache.add_compile_cache_args(parser)
    args = parser.parse_args()

    ctx = distributed.setup()
    n_dev = jax.device_count()
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(
        n_dev, tp=args.tp, sp=args.sp, fsdp=args.fsdp, ep=args.ep))
    moe = None
    if args.moe_experts:
        from batch_shipyard_tpu.models.moe import MoEConfig
        moe = MoEConfig(num_experts=args.moe_experts,
                        d_model=args.d_model, d_ff=args.d_ff,
                        dtype=jnp.bfloat16)
    config = train_mod.make_transformer_config(
        mesh, vocab_size=args.vocab, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        d_head=args.d_model // args.n_heads, d_ff=args.d_ff,
        max_seq_len=args.seq_len, dtype=jnp.bfloat16,
        moe=moe, moe_every=args.moe_every,
        quantize_matmuls=args.int8,
        remat=not args.no_remat)
    # Persistent compile cache: identity-keyed to this mesh + model
    # config so pool-wide seeding never ships entries that can only
    # miss; must be enabled BEFORE the first jit (the harness build's
    # init compile).
    compilecache.enable_from_args(
        args, mesh_shape=dict(mesh.shape),
        model_digest=compilecache.config_digest(config))
    harness = train_mod.build_transformer_train(
        mesh, config, batch_size=args.batch, seq_len=args.seq_len)
    # --aot-precompile: the step compiles on a background thread while
    # the host builds the data pipeline below; joined before warm-up.
    join_aot = (compilecache.aot.precompile_async(harness)
                if args.aot_precompile else None)
    from batch_shipyard_tpu.data import loader
    rng = np.random.RandomState(jax.process_index())
    local_batch = args.batch // jax.process_count()
    batch = loader.place_global({
        "tokens": np.asarray(
            rng.randint(0, args.vocab, (local_batch, args.seq_len)),
            np.int32),
        "targets": np.asarray(
            rng.randint(0, args.vocab, (local_batch, args.seq_len)),
            np.int32),
    }, harness.batch_sharding)
    params, opt_state = harness.params, harness.opt_state
    ckpt = checkpoint.TrainCheckpointer.from_args(args)
    params, opt_state, start_step = ckpt.restore(params, opt_state)
    if start_step:
        distributed.log(ctx, f"resumed from step {start_step}")
    if join_aot is not None:
        join_aot()
    # Goodput program phases: the warm-up loop is jit compile time
    # (compile badput, stamped with the cache's hit/saved detail);
    # the measured loop is the productive step window, stamped with
    # step + token counters so the accounting engine can price
    # preemption-recovery rework after a restore.
    with goodput_events.phase(goodput_events.PROGRAM_COMPILE,
                              what="jit_warmup",
                              steps=args.warmup) as warm_attrs, \
            compilecache.tracked(warm_attrs, "transformer_warmup"):
        for _ in range(args.warmup):
            params, opt_state, metrics = harness.step(params,
                                                      opt_state, batch)
            float(metrics["loss"])  # hard sync
    start = time.perf_counter()
    # Step windows are flushed INCREMENTALLY at every checkpoint
    # boundary (not one span over the whole loop): a window recorded
    # only on clean exit would vanish with a preempted attempt, and
    # the accounting engine's replayed-step rework pricing needs the
    # crashed attempt's completed progress to survive on disk.
    window = {"step": start_step, "time": time.time()}

    def _flush_window(end_step: int) -> None:
        if end_step > window["step"]:
            goodput_events.record(
                goodput_events.PROGRAM_STEP_WINDOW,
                window["time"], time.time(),
                step_start=window["step"], step_end=end_step,
                tokens=args.batch * args.seq_len
                * (end_step - window["step"]))
        window["step"] = end_step
        window["time"] = time.time()

    # On-demand profiling (trace/profiling.py): `shipyard jobs
    # profile` drops a request file the agent forwards; the next N
    # steps run under jax.profiler.trace. O(one stat) per step while
    # disarmed.
    from batch_shipyard_tpu.trace.profiling import StepProfiler
    profiler = StepProfiler()
    for step_num in range(start_step, start_step + args.steps):
        profiler.tick(step_num)
        params, opt_state, metrics = harness.step(params,
                                                  opt_state, batch)
        # Cooperative preemption: drain at this step boundary, force
        # a COMMITTED checkpoint, exit with the distinct preempted
        # status — the agent requeues at full budget and the rerun
        # resumes exactly here (zero lost steps beyond the barrier).
        if ckpt.maybe_preempt(step_num + 1, params, opt_state):
            _flush_window(step_num + 1)
            profiler.close()
            return preemption.EXIT_PREEMPTED
        if ckpt.due(step_num + 1):
            _flush_window(step_num + 1)
            # Sync: pays the whole persist here (checkpoint badput).
            # --async-checkpoint: pays only the snapshot; the persist
            # overlaps the next steps' windows.
            ckpt.step_save(step_num + 1, params, opt_state)
            window["time"] = time.time()  # save span is not steps
    loss = float(metrics["loss"])  # hard sync before the final flush
    profiler.close()
    _flush_window(start_step + args.steps)
    elapsed = time.perf_counter() - start
    # Exit save dedups against the loop's cadenced save of the same
    # step, then drains any in-flight async persist.
    ckpt.finalize(start_step + args.steps, params, opt_state)
    tokens_per_sec = args.batch * args.seq_len * args.steps / elapsed
    distributed.log(ctx, (
        f"transformer: mesh={dict(mesh.shape)} "
        f"{tokens_per_sec:.0f} tok/s, loss={loss:.4f}, "
        f"{elapsed / args.steps * 1000:.1f} ms/step"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

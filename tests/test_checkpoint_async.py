"""Zero-stall async checkpointing (workloads/checkpoint.py):
equivalence with the sync path, blocking-time win, crash safety of
the background writer, retention GC invariants, the stale-step save
guard, the shared TrainCheckpointer driver, and the fakepod e2e
goodput attribution of the overlapped persist.

Everything runs on CPU with small pytrees; the "large" pytree for the
blocking-time measurement is a few MB — big enough that Orbax's
serialize+fsync dominates the device→host snapshot by orders of
magnitude, small enough to keep the test in the tier-1 budget."""

import json
import os
import time

import numpy as np
import pytest

from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.goodput import events as gp
from batch_shipyard_tpu.workloads import checkpoint

pytest.importorskip("orbax.checkpoint")


def _tree(seed: int, elems: int = 64):
    rng = np.random.RandomState(seed)
    params = {"w1": rng.randn(elems).astype(np.float32),
              "w2": rng.randn(2, elems).astype(np.float32)}
    opt = {"m": np.zeros((elems,), np.float32),
           "count": np.full((1,), seed, np.int32)}
    return params, opt


def _commit_fake(ckpt_dir, step):
    """A committed checkpoint shell (dir + marker) without paying an
    Orbax write — for pure protocol/retention tests."""
    os.makedirs(os.path.join(str(ckpt_dir), f"step_{step:08d}"),
                exist_ok=True)
    marker = os.path.join(str(ckpt_dir),
                          f"step_{step:08d}." + checkpoint.COMMIT_MARKER)
    with open(marker, "w", encoding="utf-8") as fh:
        fh.write("ts")


# ------------------------- sync/async equivalence ----------------------

def test_async_save_restores_identical_state(tmp_path):
    params, opt = _tree(1)
    sync_dir = str(tmp_path / "sync")
    async_dir = str(tmp_path / "async")
    assert checkpoint.save(sync_dir, 1, params, opt) is not None
    with checkpoint.AsyncCheckpointManager(async_dir) as manager:
        assert manager.save(1, params, opt) is not None
        manager.wait_until_finished()
        assert checkpoint.latest_step(async_dir) == 1
        assert checkpoint.is_committed(async_dir, 1)
        r_sync = checkpoint.restore(sync_dir, params, opt)
        r_async = manager.restore(params, opt)
    assert r_sync is not None and r_async is not None
    assert r_sync[2] == r_async[2] == 1
    for tree_s, tree_a in ((r_sync[0], r_async[0]),
                           (r_sync[1], r_async[1])):
        import jax
        leaves_s = jax.tree_util.tree_leaves(tree_s)
        leaves_a = jax.tree_util.tree_leaves(tree_a)
        assert len(leaves_s) == len(leaves_a)
        for leaf_s, leaf_a in zip(leaves_s, leaves_a):
            np.testing.assert_array_equal(np.asarray(leaf_s),
                                          np.asarray(leaf_a))


def test_async_blocking_time_beats_sync(tmp_path):
    """The acceptance criterion: per-save blocking time of the async
    pipeline (snapshot + enqueue) is strictly less than a full sync
    save of the same synthetic large pytree."""
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    params = {f"w{i}": jnp.asarray(
        rng.randn(128, 1024).astype(np.float32)) for i in range(4)}
    opt = {f"m{i}": jnp.zeros((128, 1024), jnp.float32)
           for i in range(4)}
    sync_ms = []
    for i in range(2):
        t0 = time.perf_counter()
        checkpoint.save(str(tmp_path / "sync"), i + 1, params, opt)
        sync_ms.append((time.perf_counter() - t0) * 1e3)
    async_ms = []
    with checkpoint.AsyncCheckpointManager(
            str(tmp_path / "async")) as manager:
        for i in range(2):
            t0 = time.perf_counter()
            manager.save(i + 1, params, opt)
            async_ms.append((time.perf_counter() - t0) * 1e3)
            # Drain OUTSIDE the timed region: each sample measures a
            # clean snapshot+enqueue, not the depth-1 queue wait.
            manager.wait_until_finished()
    assert min(async_ms) < min(sync_ms)
    assert checkpoint.latest_step(str(tmp_path / "async")) == 2


# ------------------------------ crash safety ---------------------------

def test_failed_background_save_reraises_and_keeps_latest(
        tmp_path, monkeypatch):
    """Writer dies mid-persist: the failure re-raises at the next
    drain/enqueue, latest_step still answers the previous committed
    step, and the torn staging dir is never pickable."""
    params, opt = _tree(2)
    ckpt_dir = str(tmp_path / "ckpt")
    assert checkpoint.save(ckpt_dir, 1, params, opt) is not None

    class BoomCheckpointer:
        def save(self, path, state, force=True):
            # Fault-injected filesystem error mid-write: staging dir
            # exists with partial contents when the failure hits.
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "partial"), "w") as fh:
                fh.write("torn")
            raise OSError("disk gone")

    with checkpoint.AsyncCheckpointManager(ckpt_dir) as manager:
        monkeypatch.setattr(checkpoint, "_checkpointer",
                            BoomCheckpointer)
        assert manager.save(2, params, opt) is not None  # enqueued
        with pytest.raises(OSError, match="disk gone"):
            manager.wait_until_finished()
        # Disk truth is intact: previous committed step still wins,
        # the torn staging dir is invisible.
        assert checkpoint.latest_step(ckpt_dir) == 1
        assert not checkpoint.is_committed(ckpt_dir, 2)
        # Failure also surfaces at the next ENQUEUE: save 3 fails in
        # the background, save 4 re-raises before enqueueing on top
        # of the hole.
        assert manager.save(3, params, opt) is not None
        with pytest.raises(OSError, match="disk gone"):
            manager.save(4, params, opt)
        # After the raise the failed step is retryable (the guard
        # fell back to disk truth), and a healed filesystem persists
        # it durably. Restores resume from the last DURABLE step
        # until then.
        monkeypatch.undo()
        restored = checkpoint.restore(ckpt_dir, params, opt)
        assert restored is not None and restored[2] == 1
        assert manager.save(2, params, opt) is not None
        manager.wait_until_finished()
    assert checkpoint.latest_step(ckpt_dir) == 2


# ------------------------------- retention -----------------------------

def test_retention_gc_keeps_newest_and_inflight(tmp_path):
    ckpt = tmp_path / "ckpt"
    for step in (1, 2, 3, 4):
        _commit_fake(ckpt, step)
    staging = ckpt / ".tmp_step_00000005"
    staging.mkdir()
    removed = checkpoint.retention_gc(str(ckpt), keep_last=2)
    assert removed == [1, 2]
    assert checkpoint.latest_step(str(ckpt)) == 4
    assert checkpoint.is_committed(str(ckpt), 3)
    assert not checkpoint.is_committed(str(ckpt), 1)
    assert staging.is_dir()  # in-flight staging never touched
    # keep_last >= count: nothing to do.
    assert checkpoint.retention_gc(str(ckpt), keep_last=10) == []
    assert checkpoint.latest_step(str(ckpt)) == 4


def test_retention_gc_spares_legacy_unmarked_dirs(tmp_path):
    """Pre-marker dirs cannot be proven durable, so retention must
    never delete them (they may be a fleet's only resume points)."""
    legacy = tmp_path / "legacy"
    (legacy / "step_00000005").mkdir(parents=True)
    (legacy / "step_00000009").mkdir()
    assert checkpoint.retention_gc(str(legacy), keep_last=1) == []
    assert checkpoint.latest_step(str(legacy)) == 9


def test_async_manager_runs_retention_in_writer(tmp_path):
    params, opt = _tree(3)
    ckpt_dir = str(tmp_path / "ckpt")
    with checkpoint.AsyncCheckpointManager(ckpt_dir,
                                           keep_last=2) as manager:
        for step in (1, 2, 3):
            manager.save(step, params, opt)
        manager.wait_until_finished()
    assert checkpoint.latest_step(ckpt_dir) == 3
    assert checkpoint.is_committed(ckpt_dir, 2)
    assert not checkpoint.is_committed(ckpt_dir, 1)
    assert not os.path.isdir(os.path.join(ckpt_dir, "step_00000001"))


# ------------------------------ save guard -----------------------------

def test_sync_save_guard_skips_stale_step(tmp_path):
    params, opt = _tree(4)
    ckpt_dir = str(tmp_path / "ckpt")
    assert checkpoint.save(ckpt_dir, 5, params, opt) is not None
    # Re-saving the restore point (or older) burns a full save for
    # nothing: log and skip.
    assert checkpoint.save(ckpt_dir, 5, params, opt) is None
    assert checkpoint.save(ckpt_dir, 3, params, opt) is None
    assert checkpoint.save(ckpt_dir, 5, params, opt,
                           force=True) is not None
    assert checkpoint.save(ckpt_dir, 6, params, opt) is not None
    assert checkpoint.latest_step(ckpt_dir) == 6


def test_async_save_guard_covers_inflight_steps(tmp_path):
    params, opt = _tree(5)
    ckpt_dir = str(tmp_path / "ckpt")
    with checkpoint.AsyncCheckpointManager(ckpt_dir) as manager:
        assert manager.save(7, params, opt) is not None
        # Same step again while (possibly) still in flight: skipped
        # without waiting on the queue.
        assert manager.save(7, params, opt) is None
        assert manager.save(6, params, opt) is None
        manager.wait_until_finished()
        assert manager.save(7, params, opt) is None  # now committed
    assert checkpoint.latest_step(ckpt_dir) == 7


def test_train_checkpointer_finalize_dedups_final_save(
        tmp_path, monkeypatch):
    """The duplicate-final-save fix: when steps %% checkpoint_every
    == 0 the loop's cadenced save already committed the final step —
    the exit save must be skipped, sync and async alike."""
    persists = []
    real_persist = checkpoint._persist_state

    def counting_persist(ckpt_dir, step, state, mesh_meta=None):
        persists.append(step)
        return real_persist(ckpt_dir, step, state,
                            mesh_meta=mesh_meta)

    monkeypatch.setattr(checkpoint, "_persist_state",
                        counting_persist)
    params, opt = _tree(6)
    for name, use_async in (("sync", False), ("async", True)):
        persists.clear()
        tc = checkpoint.TrainCheckpointer(
            str(tmp_path / name), every=2, use_async=use_async)
        for step_num in range(4):
            tc.step_save(step_num + 1, params, opt)
        tc.finalize(4, params, opt)
        assert persists == [2, 4], name
        assert checkpoint.latest_step(str(tmp_path / name)) == 4
    # Off-cadence end (5 steps, every=2): finalize DOES save step 5.
    persists.clear()
    tc = checkpoint.TrainCheckpointer(str(tmp_path / "odd"), every=2,
                                      use_async=True)
    for step_num in range(5):
        tc.step_save(step_num + 1, params, opt)
    tc.finalize(5, params, opt)
    assert persists == [2, 4, 5]


def test_train_checkpointer_restore_roundtrip(tmp_path):
    params, opt = _tree(7)
    ckpt_dir = str(tmp_path / "ckpt")
    tc = checkpoint.TrainCheckpointer(ckpt_dir, every=0,
                                      use_async=True)
    p, o, start = tc.restore(params, opt)
    assert start == 0 and p is params  # nothing committed yet
    tc.finalize(9, params, opt)
    tc2 = checkpoint.TrainCheckpointer(ckpt_dir, use_async=True)
    p2, _o2, start2 = tc2.restore(params, opt)
    assert start2 == 9
    np.testing.assert_array_equal(np.asarray(p2["w1"]), params["w1"])
    tc2.finalize(9, params, opt)  # guard: no duplicate write
    disabled = checkpoint.TrainCheckpointer(None)
    assert disabled.restore(params, opt) == (params, opt, 0)
    assert not disabled.due(10)
    disabled.finalize(10, params, opt)  # no-op


# ------------------- goodput attribution (events) ----------------------

def test_async_save_emits_snapshot_and_async_phases(
        tmp_path, monkeypatch):
    goodput_file = tmp_path / "gp.jsonl"
    monkeypatch.setenv(gp.GOODPUT_FILE_ENV, str(goodput_file))
    params, opt = _tree(8)
    with checkpoint.AsyncCheckpointManager(
            str(tmp_path / "ckpt")) as manager:
        manager.save(1, params, opt)
        manager.wait_until_finished()
    events = [json.loads(line) for line in
              goodput_file.read_text().splitlines()]
    by_kind = {e["kind"]: e for e in events}
    assert gp.PROGRAM_CHECKPOINT_SAVE in by_kind
    assert gp.PROGRAM_CHECKPOINT_ASYNC in by_kind
    snapshot = by_kind[gp.PROGRAM_CHECKPOINT_SAVE]
    persist = by_kind[gp.PROGRAM_CHECKPOINT_ASYNC]
    assert snapshot["attrs"].get("mode") == "snapshot"
    # The persist STARTS inside/at the blocking snapshot (enqueue)
    # and runs past it in the background.
    assert persist["end"] >= snapshot["start"]


# --------------------------- e2e on fakepod ----------------------------

@pytest.fixture()
def fakepod_env():
    from batch_shipyard_tpu.config import settings as settings_mod
    from batch_shipyard_tpu.pool import manager as pool_mgr
    from batch_shipyard_tpu.state.memory import MemoryStateStore
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    conf = {"pool_specification": {
        "id": "pool1", "substrate": "fake",
        "tpu": {"accelerator_type": "v5litepod-16", "num_slices": 1},
        "task_slots_per_node": 1,
        "max_wait_time_seconds": 30,
    }}
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store)
    pool = settings_mod.pool_settings(conf)
    pool_mgr.create_pool(store, substrate, pool,
                         settings_mod.global_settings({}), conf)
    yield store, substrate, pool
    substrate.stop_all()


def test_e2e_async_checkpoint_badput_is_snapshot_only(fakepod_env):
    """The acceptance run: a fakepod job whose payload records a step
    window, a snapshot-only checkpoint_save, and an overlapped
    checkpoint_async persist whose tail outlives the window. The
    report must charge ONLY the snapshot as checkpoint badput, show
    the persist in the overlapped bucket, and still partition wall
    clock within 1%."""
    from batch_shipyard_tpu.config import settings as settings_mod
    from batch_shipyard_tpu.jobs import manager as jobs_mgr
    store, substrate, pool = fakepod_env
    payload = (
        "python3 -c \"import json,os,time; t=time.time(); "
        "fh=open(os.environ['SHIPYARD_GOODPUT_FILE'],'a'); "
        "w=lambda k,s,e,a: fh.write(json.dumps({'kind':k,'start':s,"
        "'end':e,'attrs':a})+chr(10)); "
        "w('step_window',t,t+0.30,{'step_start':0,'step_end':30,"
        "'tokens':300}); "
        "w('checkpoint_save',t+0.10,t+0.11,{'step':10,"
        "'mode':'snapshot'}); "
        "w('checkpoint_async',t+0.11,t+0.40,{'step':10}); "
        "fh.close(); time.sleep(0.1)\"")
    jobs_mgr.add_jobs(store, pool, settings_mod.job_settings_list(
        {"job_specifications": [{
            "id": "jasync", "tasks": [{"command": payload}]}]}))
    tasks = jobs_mgr.wait_for_tasks(store, "pool1", "jasync",
                                    timeout=30)
    assert tasks[0]["state"] == "completed"
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        kinds = {e["kind"] for e in gp.query(store, "pool1",
                                             job_id="jasync")}
        if gp.PROGRAM_CHECKPOINT_ASYNC in kinds:
            break
        time.sleep(0.1)
    assert gp.PROGRAM_CHECKPOINT_ASYNC in kinds
    assert gp.PROGRAM_CHECKPOINT_SAVE in kinds
    report = accounting.job_report(store, "pool1", "jasync")
    # Checkpoint badput is the snapshot ONLY — the overlapped persist
    # is not a stall.
    assert report["badput_seconds"]["checkpoint"] == pytest.approx(
        0.01, abs=0.005)
    # The persist's window-covered part stayed productive; its tail
    # past the step window is the overlapped bucket.
    assert report["overlapped_seconds"][
        "checkpoint_async"] == pytest.approx(0.10, abs=0.02)
    # Partition stays exact within 1%.
    total = (report["productive_seconds"]
             + sum(report["badput_seconds"].values())
             + sum(report["overlapped_seconds"].values()))
    assert total == pytest.approx(report["wall_seconds"], rel=0.01)
    table = accounting.waterfall_table(report)
    assert "~checkpoint_async" in table

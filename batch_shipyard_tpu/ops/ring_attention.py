"""Ring attention: exact attention over sequence shards via an ICI
ppermute ring.

Long-context mechanism (SURVEY.md section 5.7 net-new design space):
the sequence is sharded over the ``sp`` mesh axis; each device holds
its Q shard permanently and rotates KV shards around the ring,
accumulating exact attention with the online-softmax update from
ops/attention.py. After sp steps every Q position has attended to the
full global sequence — memory per device stays O(T/sp), and the KV
rotation (lax.ppermute, riding adjacent-neighbor ICI links) overlaps
with the per-block attention compute under XLA's scheduler.

Differentiable end-to-end (scan + ppermute have transposable rules),
so the same code path serves training — this is how the framework runs
contexts larger than one chip's HBM.

Use under shard_map with q/k/v sharded as P(('dp','fsdp'), 'sp', None,
None); models/transformer.py wires this automatically when the mesh
has sp > 1.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from batch_shipyard_tpu.utils.compat import shard_map

from batch_shipyard_tpu.ops import attention as attn_ops
from batch_shipyard_tpu.ops import kernel_select


RING_IMPLS = ("pallas_dma", "flash", "xla")


def resolve_ring_impl(impl: str = "auto") -> str:
    """Resolve 'auto' to a concrete ring implementation.

    Priority: explicit impl > SHIPYARD_RING_IMPL env > the
    KERNEL_VALIDATION.json marker via ops/kernel_select
    ('pallas_dma' — flash kernels + async-DMA ring permute — only
    when BOTH the ring_collectives and flash_ring checks passed on a
    TPU backend; 'flash' when flash_ring alone passed; both require
    the current backend to be tpu) > 'xla'. CPU always resolves to
    'xla' — pallas interpret mode aborts inside shard_map there.
    """
    if impl != "auto":
        return impl
    env = os.environ.get("SHIPYARD_RING_IMPL")
    if env:
        if env not in RING_IMPLS:
            raise ValueError(
                f"SHIPYARD_RING_IMPL={env!r}: must be one of "
                f"{', '.join(RING_IMPLS)}")
        return env
    resolved = kernel_select.resolve_auto("flash_ring",
                                          pallas_impl="flash")
    if resolved == "flash":
        # The DMA-permute tier needs its own silicon proof on top of
        # the flash one (tools/tpu_checks.py check 'ring_collectives').
        return kernel_select.resolve_auto("ring_collectives",
                                          pallas_impl="pallas_dma",
                                          fallback="flash")
    return resolved


def _flash_ring_rotation(q, k_cur, v_cur, my_idx, src, causal: bool):
    """One ring rotation's partial attention with the flash kernels.

    Each rotation's masking regime is one of exactly three static
    cases — fully masked (KV from a later shard), diagonal (own
    shard: causal), fully visible (earlier shard) — selected with
    lax.switch, so the offset-free flash kernels apply unchanged and
    partials merge in logsumexp space. my_idx/src may be traced (ring
    body) or concrete (single-device virtual-shard simulation).
    """

    def masked(_q, _k, _v):
        return attn_ops.masked_attention_block(_q)

    def diagonal(_q, _k, _v):
        return attn_ops.flash_attention_with_lse(_q, _k, _v, True)

    def full(_q, _k, _v):
        return attn_ops.flash_attention_with_lse(_q, _k, _v, False)

    if not causal:
        return full(q, k_cur, v_cur)
    case = jnp.where(src > my_idx, 0,
                     jnp.where(src == my_idx, 1, 2))
    return jax.lax.switch(case, (masked, diagonal, full),
                          q, k_cur, v_cur)


def _ring_attention_local_flash(q, k, v, axis_name: str, causal: bool,
                                kv_permute: str = "ppermute",
                                mesh_axis_names=None):
    """Per-shard ring body using the Pallas flash kernels (see
    _flash_ring_rotation for the 3-case selection).

    kv_permute: 'ppermute' rotates KV shards with lax.ppermute (XLA
    schedules the transfer); 'dma' uses the async-remote-DMA Pallas
    permute kernel (ops/ring_collectives.ring_permute_pair) — the
    impl='pallas_dma' tier, TPU silicon only.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def rotate(k_cur, v_cur):
        if kv_permute == "dma":
            from batch_shipyard_tpu.ops import ring_collectives
            return ring_collectives.ring_permute_pair(
                k_cur, v_cur, axis_name, tuple(mesh_axis_names),
                int(axis_size))
        return (jax.lax.ppermute(k_cur, axis_name, perm),
                jax.lax.ppermute(v_cur, axis_name, perm))

    @jax.checkpoint
    def step(carry, t):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (my_idx - t) % axis_size
        o_s, lse_s = _flash_ring_rotation(q, k_cur, v_cur, my_idx,
                                          src, causal)
        o_acc, lse_acc = attn_ops.merge_attention_blocks(
            o_acc, lse_acc, o_s, lse_s)
        k_nxt, v_nxt = rotate(k_cur, v_cur)
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    o0, lse0 = attn_ops.masked_attention_block(q)
    (o, _lse, _, _), _ = jax.lax.scan(
        step, (o0, lse0, k, v), jnp.arange(axis_size))
    return o


def ring_attention_virtual_shards(q, k, v, sp: int, causal: bool = True):
    """Run the flash-ring algorithm — the SAME 3-case rotation +
    logsumexp merge the shard_map body uses — over sp virtual sequence
    shards on a single device.

    This exists so the flash ring path is exercisable on one real TPU
    chip (pallas interpret mode aborts inside shard_map on CPU, and
    multi-chip hardware is not always at hand): tools/tpu_checks.py
    runs it against the oracle, forward and backward, on the chip.
    """
    if q.shape[1] % sp or k.shape[1] != q.shape[1]:
        raise ValueError(
            f"sequence length {q.shape[1]} (kv {k.shape[1]}) must be "
            f"equal and divisible by sp={sp}")
    t_local = q.shape[1] // sp
    outs = []
    for my_idx in range(sp):
        q_s = jax.lax.dynamic_slice_in_dim(q, my_idx * t_local,
                                           t_local, axis=1)
        o_acc, lse_acc = attn_ops.masked_attention_block(q_s)
        for t in range(sp):
            src = (my_idx - t) % sp
            k_s = jax.lax.dynamic_slice_in_dim(k, src * t_local,
                                               t_local, axis=1)
            v_s = jax.lax.dynamic_slice_in_dim(v, src * t_local,
                                               t_local, axis=1)
            o_s, lse_s = _flash_ring_rotation(
                q_s, k_s, v_s, jnp.int32(my_idx), jnp.int32(src),
                causal)
            o_acc, lse_acc = attn_ops.merge_attention_blocks(
                o_acc, lse_acc, o_s, lse_s)
        outs.append(o_acc)
    return jnp.concatenate(outs, axis=1)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-shard body (runs inside shard_map). q/k/v: [B, Tl, H, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Rematerialize each step: without this the scan's backward saves
    # every rotation's score/probability matrices (O(T_local^2) fp32
    # per step x sp steps), defeating ring attention's O(T/sp) memory
    # promise — the entire point of sequence parallelism.
    @jax.checkpoint
    def step(carry, t):
        o, m, l, k_cur, v_cur = carry
        # After t rotations we hold the KV shard originally on
        # (my_idx - t) mod axis_size.
        src = (my_idx - t) % axis_size
        o, m, l = attn_ops.attention_block_update(
            q, k_cur, v_cur, o, m, l, causal=causal,
            q_offset=my_idx * t_local, kv_offset=src * t_local,
            scale=scale)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, m, l, k_nxt, v_nxt), None

    o, m, l = attn_ops.attention_init(q)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o, m, l, k, v), jnp.arange(axis_size))
    return attn_ops.attention_finalize(q, o, m, l)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True,
                   batch_axes: tuple[str, ...] = ("dp", "fsdp"),
                   head_axis: str = "tp",
                   impl: str = "auto"):
    """Global-view entry: q/k/v are [B, T, H, D] global arrays; returns
    the exact attention output with T sharded over axis_name.

    impl: 'pallas_dma' (flash kernels per rotation + async-remote-DMA
    KV permute — the deepest on-chip tier), 'flash' (Pallas kernels
    per rotation, lax.ppermute rotation), 'xla' (pure-XLA online
    softmax — runs anywhere), or 'auto' (resolved by
    resolve_ring_impl: the validated Pallas tiers on a TPU backend
    once the KERNEL_VALIDATION.json marker records their on-chip
    passes, else xla).
    """
    impl = resolve_ring_impl(impl)
    if impl in ("flash", "pallas_dma"):
        t_local = q.shape[1] // mesh.shape[axis_name]
        if not attn_ops.flash_shapes_ok(t_local, t_local):
            raise ValueError(
                f"local shard length {t_local} does not tile the "
                f"flash blocks; use impl='xla'")
    if impl == "pallas_dma":
        body = functools.partial(
            _ring_attention_local_flash, kv_permute="dma",
            mesh_axis_names=mesh.axis_names)
    else:
        body = (_ring_attention_local_flash if impl == "flash"
                else _ring_attention_local)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = shard_map(
        functools.partial(body, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # The online-softmax carry is initialized from constants
        # (attention_init zeros), which varying-manual-axes tracking
        # would reject against the per-step varying update.
        check_vma=False)
    return fn(q, k, v)


def ring_attention_inside_shard_map(q, k, v, axis_name: str = "sp",
                                    causal: bool = True):
    """For callers already inside a shard_map (e.g. a fully shard_mapped
    train step): per-shard inputs, per-shard output."""
    return _ring_attention_local(q, k, v, axis_name, causal)

#!/bin/bash
# No errexit on purpose: a failed probe is this loop's NORMAL branch
# (the accelerator is usually unreachable); every exit path is
# handled explicitly.
# shipyard-lint: disable-file=shell-strict-mode
# Periodic TPU-availability probe (VERDICT r2 order #1: retry
# continuously, don't leave the bench to the end-of-round snapshot).
# Loops until the accelerator answers, logging every attempt to
# BENCH_ATTEMPTS.log; on success hands off to the one-shot silicon
# proof pipeline (tools/silicon_proof.py: kernel validation -> Pallas
# auto-impl flip -> XLA tuning A/B -> full bench with MFU%) and exits.
cd /root/repo || exit 1
LOG=BENCH_ATTEMPTS.log
while true; do
    TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
    timeout 300 python - <<'EOF' > /tmp/probe_out.txt 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("OK", jax.devices())
EOF
    RC=$?
    if [ $RC -eq 0 ] && grep -q '^OK' /tmp/probe_out.txt; then
        echo "$TS probe OK — running silicon proof pipeline" >> "$LOG"
        timeout 7200 python tools/silicon_proof.py \
            > /tmp/silicon_proof_out.txt 2>&1
        PRC=$?
        echo "$TS silicon_proof rc=$PRC: \
$(tail -2 /tmp/silicon_proof_out.txt | head -1)" >> "$LOG"
        exit 0
    fi
    echo "$TS probe FAILED rc=$RC: $(tail -1 /tmp/probe_out.txt)" \
        >> "$LOG"
    sleep 600
done

"""MFU accounting tests: the analytic transformer FLOPs model is
oracle-tested against a real model.init parameter count so the bench's
MFU denominator can never drift from the model code; device-kind ->
generation mapping feeds the peak-FLOPs lookup."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.parallel import mfu, topology


def test_transformer_param_count_matches_model_init():
    from batch_shipyard_tpu.models import transformer as tfm
    config = tfm.TransformerConfig(
        vocab_size=1024, d_model=128, n_layers=2, n_heads=4,
        d_head=32, d_ff=256, max_seq_len=64)
    model = tfm.TransformerLM(config)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    actual = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(params))
    assert mfu.transformer_param_count(config) == actual


def test_resnet50_flops_ballpark():
    # torchvision-standard: ~4.09 GMACs fwd at 224 -> ~24.5 GFLOPs
    # per trained image (3x fwd, 2 FLOPs/MAC).
    f = mfu.resnet50_train_flops_per_image(224)
    assert 2.3e10 < f < 2.6e10
    # Quadratic spatial scaling.
    assert mfu.resnet50_train_flops_per_image(112) == pytest.approx(
        f / 4)


def test_transformer_flops_per_token_dominated_by_6n():
    from batch_shipyard_tpu.models import transformer as tfm
    config = tfm.TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=12, n_heads=16,
        d_head=64, d_ff=2816, max_seq_len=2048)
    n = mfu.transformer_param_count(config)
    f = mfu.transformer_train_flops_per_token(config, seq_len=2048)
    assert f > 6 * n
    # Attention term: 6*L*T*d for causal.
    assert f - 6 * n == pytest.approx(
        6 * config.n_layers * 2048 * config.d_model)
    # Non-causal doubles the attention term.
    f_nc = mfu.transformer_train_flops_per_token(
        config, seq_len=2048, causal=False)
    assert f_nc - 6 * n == pytest.approx(2 * (f - 6 * n))


def test_mfu_pct_math_and_unknown_peak():
    # 100 items/s at 1e9 FLOPs/item vs 1 TFLOP/s peak = 10%.
    assert mfu.mfu_pct(100.0, 1e9, 1.0) == pytest.approx(10.0)
    assert mfu.mfu_pct(100.0, 1e9, None) is None
    assert mfu.mfu_pct(100.0, 1e9, 0.0) is None


@pytest.mark.parametrize("kind,gen", [
    ("TPU v2", "v2"),
    ("TPU v3", "v3"),
    ("TPU v4", "v4"),
    ("TPU v5 lite", "v5litepod"),
    ("TPU v5e", "v5litepod"),
    ("TPU v5p", "v5p"),
    ("TPU v6 lite", "v6e"),
    ("TPU v6e", "v6e"),
])
def test_generation_for_device_kind(kind, gen):
    resolved = topology.generation_for_device_kind(kind)
    assert resolved is not None and resolved.name == gen
    assert topology.peak_bf16_tflops_for_device_kind(kind) == \
        resolved.bf16_tflops_per_chip


def test_non_tpu_device_kind_maps_to_none():
    assert topology.generation_for_device_kind("cpu") is None
    assert topology.generation_for_device_kind(
        "NVIDIA A100-SXM4-40GB") is None
    assert topology.peak_bf16_tflops_for_device_kind("cpu") is None

"""Localhost substrate: real subprocess node agents over the localfs
store (the path that drives locally attached TPU hardware)."""

import pytest

from batch_shipyard_tpu import fleet
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr


@pytest.fixture()
def ctx(tmp_path):
    creds = {"credentials": {"storage": {
        "backend": "localfs", "root": str(tmp_path / "store")}}}
    pool_conf = {"pool_specification": {
        "id": "lh", "substrate": "localhost",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "max_wait_time_seconds": 60}}
    context = fleet.load_context(extra={"credentials": creds,
                                        "pool": pool_conf})
    yield context
    try:
        pool_mgr.delete_pool(context.store, context.substrate(), "lh")
    except Exception:
        pass


def test_localhost_end_to_end_and_module_import(ctx):
    nodes = fleet.action_pool_add(ctx)
    assert len(nodes) == 2
    jobs_conf = {"job_specifications": [{
        "id": "lhjob",
        "tasks": [
            {"id": "echo", "command": "echo subprocess-agent"},
            # Tasks run with cwd=task_dir: the framework package must
            # still be importable (PYTHONPATH injected by the
            # substrate — this is what lets tasks launch
            # batch_shipyard_tpu.workloads.* on dev hosts).
            {"id": "mod", "command":
             "python -c 'import batch_shipyard_tpu; print(\"mod-ok\")'"},
        ],
    }]}
    import yaml
    ctx.configs["jobs"] = yaml.safe_load(yaml.safe_dump(jobs_conf))
    fleet.action_jobs_add(ctx)
    tasks = {t["_rk"]: t for t in jobs_mgr.wait_for_tasks(
        ctx.store, "lh", "lhjob", timeout=90)}
    assert tasks["echo"]["state"] == "completed", tasks
    assert tasks["mod"]["state"] == "completed", tasks
    out = jobs_mgr.get_task_output(ctx.store, "lh", "lhjob", "mod")
    assert out.strip() == b"mod-ok"

"""Input pipeline tests: sharded dataset partitioning, batching across
shard boundaries, device prefetch."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batch_shipyard_tpu.data import loader


@pytest.fixture()
def shard_dir(tmp_path):
    loader.write_synthetic_imagenet_shards(
        str(tmp_path), num_shards=4, per_shard=100, image_size=8,
        num_classes=10)
    return str(tmp_path)


def test_batches_cross_shard_boundaries(shard_dir):
    ds = loader.ShardedDataset(shard_dir, batch_size=64,
                               process_index=0, process_count=1,
                               loop=False)
    batches = list(ds)
    # 400 samples / 64 -> 6 full batches (tail dropped at epoch end).
    assert len(batches) == 6
    for batch in batches:
        assert batch["images"].shape == (64, 8, 8, 3)
        assert batch["labels"].shape == (64,)


def test_process_partitioning(shard_dir):
    ds0 = loader.ShardedDataset(shard_dir, 10, process_index=0,
                                process_count=2, loop=False)
    ds1 = loader.ShardedDataset(shard_dir, 10, process_index=1,
                                process_count=2, loop=False)
    assert set(ds0.shards).isdisjoint(ds1.shards)
    assert len(ds0.shards) + len(ds1.shards) == 4


def test_no_shards_raises(tmp_path):
    with pytest.raises(ValueError):
        loader.ShardedDataset(str(tmp_path), 8)


def test_prefetch_to_device(shard_dir):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from batch_shipyard_tpu.parallel import mesh as mesh_mod
    mesh = mesh_mod.make_mesh(mesh_mod.auto_axis_sizes(8))
    sharding = NamedSharding(mesh, P(("dp", "fsdp", "ep", "sp", "tp")))
    ds = loader.ShardedDataset(shard_dir, batch_size=64,
                               process_index=0, process_count=1,
                               loop=False)
    seen = 0
    for batch in loader.prefetch_to_device(iter(ds), sharding,
                                           depth=2):
        assert isinstance(batch["images"], jax.Array)
        assert batch["images"].sharding.is_equivalent_to(
            sharding, ndim=batch["images"].ndim)
        total = jnp.sum(batch["labels"])
        assert np.isfinite(float(total))
        seen += 1
    assert seen == 6


def test_prefetch_propagates_errors():
    def bad():
        yield {"x": np.zeros((4,))}
        raise RuntimeError("shard corrupted")

    device = jax.devices()[0]
    it = loader.prefetch_to_device(bad(), device, depth=1)
    next(it)
    with pytest.raises(RuntimeError):
        next(it)


def test_synthetic_batches():
    it = loader.synthetic_batches(
        lambda step: {"x": np.full((2,), step)})
    assert next(it)["x"][0] == 0
    assert next(it)["x"][0] == 1

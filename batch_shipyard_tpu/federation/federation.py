"""Federation: constraint-based meta-scheduling across heterogeneous
pools (TPU pods of different shapes + CPU/GPU VM pools).

Reference analog: federation/federation.py (3237 LoC) — a daemon VM
holding a global-lock blob lease (:962), polling per-federation action
queues (:3135), filtering candidate pools with hard constraints (:1709:
pool state, vm size, location, registries, max active task backlog),
then greedy best-fit matching (:2084) with blacklisting/retry (:2786)
and poison-message zapping (fleet.py:5209).

TPU-native redesign, same architecture:
  - federations + member pools in TABLE_FEDERATIONS;
  - job actions as JSON blobs + queue messages on the federation
    queue (storage.py:1276 analog);
  - the daemon is HA via a state-store lease; constraints understand
    TPU shapes (accelerator generation, minimum chips/slices) instead
    of Azure vm sizes;
  - scheduling = hard-constraint filter -> greedy best fit by idle
    slot count -> submit through the ordinary jobs manager onto the
    chosen pool.

Job-level constraints (jobs.yaml federation_constraints block):
  pool_ids: [..]            explicit allowlist
  accelerator_generation:   e.g. 'v5litepod' / 'v6e'
  min_chips: int            total chips in the pool's slices
  min_idle_nodes: int
  max_active_task_backlog:  float ratio of queued tasks to slots
  substrate: tpu_vm|fake|localhost
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Optional

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import (
    EntityExistsError, NotFoundError, StateStore)
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)

GLOBAL_LOCK_KEY = "federation/global-lock"
LOCK_SECONDS = 30.0


# ----------------------------- client side -----------------------------

def create_federation(store: StateStore, federation_id: str,
                      force: bool = False) -> None:
    entity = {"created_at": util.datetime_utcnow_iso(), "pools": []}
    if force:
        store.upsert_entity(names.TABLE_FEDERATIONS, "fed",
                            federation_id, entity)
    else:
        try:
            store.insert_entity(names.TABLE_FEDERATIONS, "fed",
                                federation_id, entity)
        except EntityExistsError:
            raise ValueError(f"federation {federation_id} exists")


def destroy_federation(store: StateStore, federation_id: str) -> None:
    try:
        store.delete_entity(names.TABLE_FEDERATIONS, "fed",
                            federation_id)
    except NotFoundError:
        pass


def get_federation(store: StateStore, federation_id: str) -> dict:
    try:
        return store.get_entity(names.TABLE_FEDERATIONS, "fed",
                                federation_id)
    except NotFoundError:
        raise ValueError(f"federation {federation_id} does not exist")


def list_federations(store: StateStore) -> list[dict]:
    return list(store.query_entities(names.TABLE_FEDERATIONS,
                                     partition_key="fed"))


def add_pool_to_federation(store: StateStore, federation_id: str,
                           pool_id: str) -> None:
    fed = get_federation(store, federation_id)
    pools = set(fed.get("pools", []))
    pools.add(pool_id)
    store.merge_entity(names.TABLE_FEDERATIONS, "fed", federation_id,
                       {"pools": sorted(pools)},
                       if_match=fed["_etag"])


def remove_pool_from_federation(store: StateStore, federation_id: str,
                                pool_id: str) -> None:
    fed = get_federation(store, federation_id)
    pools = set(fed.get("pools", []))
    pools.discard(pool_id)
    store.merge_entity(names.TABLE_FEDERATIONS, "fed", federation_id,
                       {"pools": sorted(pools)},
                       if_match=fed["_etag"])


def submit_job_to_federation(store: StateStore, federation_id: str,
                             jobs_config: dict) -> str:
    """fed jobs add: serialize the job spec as a blob + queue message
    (batch.py:5900 generate_info_metadata + storage.py:1959 analog)."""
    get_federation(store, federation_id)
    action_id = uuid.uuid4().hex[:12]
    job_ids = [j["id"] for j in
               jobs_config.get("job_specifications", [])]
    blob_key = names.federation_job_blob_key(
        federation_id, "-".join(job_ids) or "job", action_id)
    store.put_object(blob_key, json.dumps(jobs_config).encode())
    store.put_message(names.federation_queue(federation_id),
                      json.dumps({
                          "action": "add_job", "action_id": action_id,
                          "blob_key": blob_key,
                      }).encode())
    return action_id


def zap_action(store: StateStore, federation_id: str,
               action_id: str) -> None:
    """fed jobs zap: mark a poison action so the daemon drops it
    (fleet.py:5209 analog)."""
    store.upsert_entity(names.TABLE_FEDJOBS, federation_id,
                        f"zap${action_id}", {"zapped": True})


def locate_federation_job(store: StateStore, federation_id: str,
                          job_id: str) -> str:
    """Which pool did the scheduler place this job on? (job locator
    table analog, storage.py:1276)."""
    try:
        row = store.get_entity(names.TABLE_FEDJOBS, federation_id,
                               job_id)
    except NotFoundError:
        raise ValueError(
            f"job {job_id} is not scheduled in federation "
            f"{federation_id}")
    return row["pool_id"]


def terminate_federation_job(store: StateStore, federation_id: str,
                             job_id: str) -> str:
    """fed jobs term: route the terminate to the pool the job landed
    on. Returns that pool id."""
    pool_id = locate_federation_job(store, federation_id, job_id)
    jobs_mgr.terminate_job(store, pool_id, job_id)
    return pool_id


def delete_federation_job(store: StateStore, federation_id: str,
                          job_id: str) -> str:
    """fed jobs del: route the delete and drop the locator row."""
    pool_id = locate_federation_job(store, federation_id, job_id)
    jobs_mgr.delete_job(store, pool_id, job_id)
    store.delete_entity(names.TABLE_FEDJOBS, federation_id, job_id)
    return pool_id


def list_federation_jobs(store: StateStore,
                         federation_id: str) -> list[dict]:
    return [row for row in store.query_entities(
        names.TABLE_FEDJOBS, partition_key=federation_id)
        if not row["_rk"].startswith("zap$")]


# --------------------------- constraint match --------------------------

def _pool_facts(store: StateStore, pool_id: str) -> Optional[dict]:
    """Assemble the scheduling facts for one member pool."""
    try:
        entity = pool_mgr.get_pool(store, pool_id)
    except pool_mgr.PoolNotFoundError:
        return None
    spec_raw = entity.get("spec") or {}
    try:
        pool = settings_mod.pool_settings(spec_raw)
    except (ValueError, KeyError):
        return None
    nodes = pool_mgr.list_nodes(store, pool_id)
    idle = [n for n in nodes if n.state == "idle"]
    ready = [n for n in nodes if n.state in pool_mgr.READY_STATES]
    backlog = sum(
        store.queue_length(q)
        for q in names.task_queues(pool_id, pool.task_queue_shards))
    slots = max(1, len(ready) * pool.task_slots_per_node)
    return {
        "pool_id": pool_id,
        "pool": pool,
        "state": entity.get("state"),
        "nodes_total": len(nodes),
        "nodes_idle": len(idle),
        "nodes_ready": len(ready),
        "backlog": backlog,
        "backlog_ratio": backlog / slots,
        "chips": (pool.tpu.info.num_chips * pool.tpu.num_slices
                  if pool.tpu else 0),
    }


def filter_pools_hard_constraints(
        facts: list[dict], constraints: dict) -> list[dict]:
    """Hard-constraint pool filter (:1709 analog)."""
    out = []
    allow = constraints.get("pool_ids")
    for fact in facts:
        pool = fact["pool"]
        if fact["state"] not in ("ready",):
            continue
        if allow and fact["pool_id"] not in allow:
            continue
        if constraints.get("substrate") and (
                pool.substrate != constraints["substrate"]):
            continue
        gen = constraints.get("accelerator_generation")
        if gen:
            if pool.tpu is None:
                continue
            if not pool.tpu.accelerator_type.startswith(gen) and \
                    pool.tpu.info.generation.name != gen:
                continue
        if constraints.get("min_chips") and (
                fact["chips"] < constraints["min_chips"]):
            continue
        if constraints.get("min_idle_nodes") and (
                fact["nodes_idle"] < constraints["min_idle_nodes"]):
            continue
        max_backlog = constraints.get("max_active_task_backlog")
        if max_backlog is not None and (
                fact["backlog_ratio"] > float(max_backlog)):
            continue
        out.append(fact)
    return out


def greedy_best_fit(facts: list[dict]) -> Optional[dict]:
    """Greedy best-fit pool choice (:2084 analog): most idle nodes,
    then lowest backlog ratio, then largest pool."""
    if not facts:
        return None
    return sorted(facts, key=lambda f: (
        -f["nodes_idle"], f["backlog_ratio"], -f["nodes_total"]))[0]


# ----------------------------- daemon side -----------------------------

class FederationProcessor:
    """The HA scheduler daemon (FederationProcessor :2727 analog)."""

    def __init__(self, store: StateStore, owner: Optional[str] = None,
                 poll_interval: float = 1.0,
                 action_retry_delay: float = 5.0) -> None:
        self.store = store
        self.owner = owner or f"fedproc-{uuid.uuid4().hex[:8]}"
        self.poll_interval = poll_interval
        self.action_retry_delay = action_retry_delay
        self.stop_event = threading.Event()
        self._lease = None

    # -- lock ----------------------------------------------------------

    def _hold_global_lock(self) -> bool:
        if self._lease is not None:
            try:
                self._lease = self.store.renew_lease(self._lease,
                                                     LOCK_SECONDS)
                return True
            except Exception:
                self._lease = None
        self._lease = self.store.acquire_lease(
            GLOBAL_LOCK_KEY, LOCK_SECONDS, self.owner)
        return self._lease is not None

    # -- processing ----------------------------------------------------

    def process_once(self) -> int:
        """One poll cycle over all federations; returns actions
        processed. Only the lock holder schedules (HA :962)."""
        if not self._hold_global_lock():
            return 0
        processed = 0
        for fed in list_federations(self.store):
            processed += self._process_federation_queue(fed["_rk"], fed)
        return processed

    def _is_zapped(self, federation_id: str, action_id: str) -> bool:
        try:
            self.store.get_entity(names.TABLE_FEDJOBS, federation_id,
                                  f"zap${action_id}")
            return True
        except NotFoundError:
            return False

    def _process_federation_queue(self, federation_id: str,
                                  fed: dict) -> int:
        queue = names.federation_queue(federation_id)
        processed = 0
        for msg in self.store.get_messages(
                queue, max_messages=8, visibility_timeout=60.0):
            action = json.loads(msg.payload)
            action_id = action.get("action_id", "?")
            if self._is_zapped(federation_id, action_id):
                logger.warning("dropping zapped action %s", action_id)
                self.store.delete_message(msg)
                continue
            if action.get("action") == "add_job":
                done = self._schedule_add_job(federation_id, fed,
                                              action)
                if done:
                    self.store.delete_message(msg)
                    processed += 1
                else:
                    # No eligible pool now: back off and retry
                    # (blocked-action requeue, storage.py:1331).
                    self.store.update_message(
                        msg,
                        visibility_timeout=self.action_retry_delay)
            else:
                logger.error("unknown federation action %r", action)
                self.store.delete_message(msg)
        return processed

    def _schedule_add_job(self, federation_id: str, fed: dict,
                          action: dict) -> bool:
        try:
            jobs_config = json.loads(
                self.store.get_object(action["blob_key"]))
        except NotFoundError:
            logger.error("federation action blob missing: %s",
                         action.get("blob_key"))
            return True  # unrecoverable; drop
        jobs = settings_mod.job_settings_list(jobs_config)
        facts = [f for f in (
            _pool_facts(self.store, pid) for pid in fed.get("pools", []))
            if f is not None]
        all_ok = True
        for job in jobs:
            # Idempotent retry: a job already placed by a previous
            # attempt of this (or another) action is never re-placed —
            # the placement record is insert-only.
            try:
                placed = self.store.get_entity(
                    names.TABLE_FEDJOBS, federation_id, job.id)
                logger.info(
                    "federation %s: job %s already on pool %s",
                    federation_id, job.id, placed.get("pool_id"))
                continue
            except NotFoundError:
                pass
            constraints = dict(job.federation_constraints)
            eligible = filter_pools_hard_constraints(facts, constraints)
            choice = greedy_best_fit(eligible)
            if choice is None:
                logger.info(
                    "federation %s: no eligible pool for job %s "
                    "(constraints=%s)", federation_id, job.id,
                    constraints)
                all_ok = False
                continue
            pool = choice["pool"]
            try:
                self.store.insert_entity(
                    names.TABLE_FEDJOBS, federation_id, job.id, {
                        "pool_id": pool.id,
                        "action_id": action.get("action_id"),
                        "scheduled_at": util.datetime_utcnow_iso(),
                    })
            except EntityExistsError:
                continue  # lost a race with another scheduler pass
            try:
                jobs_mgr.add_jobs(self.store, pool, [job],
                                  pool_id_override=pool.id)
            except jobs_mgr.JobExistsError:
                pass  # already scheduled by a previous attempt
            logger.info("federation %s: job %s -> pool %s",
                        federation_id, job.id, pool.id)
        return all_ok

    def run(self) -> None:
        while not self.stop_event.is_set():
            try:
                self.process_once()
            except Exception:
                logger.exception("federation processing error")
            if self.stop_event.wait(self.poll_interval):
                break
        if self._lease is not None:
            try:
                self.store.release_lease(self._lease)
            except Exception:
                pass

"""Scenario registry: named (trace, chaos schedule, fleet shape)
bundles the CLI, bench, and tests share.

A scenario is a pure builder ``(seed, nodes, tasks) -> run_sim
kwargs`` — same arguments, same simulation, byte-identical report.
The chaos inventory (chaos/plan.py ``INJECTION_KINDS``) is fully
expressible as scenario schedules: ``KIND_ADAPTERS`` maps every
injection kind to the simulator method that applies it in virtual
time (tests/test_names_consistency.py asserts the mapping covers the
inventory, minus ``SIM_EXCLUDED_KINDS``).

Scenario schema (what a builder returns, passed to
``simulator.run_sim``)::

    {"trace":        list[SimTask],   # sim/traces.py generators
     "nodes":        int,             # initial fleet width
     "slots_per_node": int,
     "injections":   tuple[Injection, ...],  # chaos schedule
     "autoscale":    bool,            # enable the autoscale tick
     "min_nodes"/"max_nodes"/"provision_seconds": fleet limits}
"""

from __future__ import annotations

from typing import Callable

from batch_shipyard_tpu.chaos.plan import ChaosPlan, INJECTION_KINDS
from batch_shipyard_tpu.sched.policy import PolicyKnobs
from batch_shipyard_tpu.sim import traces
from batch_shipyard_tpu.sim.simulator import FleetSimulator

# Every batch-pool INJECTION_KINDS entry maps to the simulator
# adapter that applies it in virtual time (the serving kinds are
# excluded below — see SIM_EXCLUDED_KINDS).
KIND_ADAPTERS: dict[str, Callable] = {
    "store_delay": FleetSimulator.chaos_store_delay,
    "store_error": FleetSimulator.chaos_store_error,
    "heartbeat_blackout": FleetSimulator.chaos_heartbeat_blackout,
    "task_kill": FleetSimulator.chaos_task_kill,
    "task_wedge": FleetSimulator.chaos_task_wedge,
    "node_preempt": FleetSimulator.chaos_node_preempt,
    "node_preempt_notice": FleetSimulator.chaos_node_preempt_notice,
    "victim_ignore_notice":
        FleetSimulator.chaos_victim_ignore_notice,
    "host_loss_resize": FleetSimulator.chaos_host_loss_resize,
    "pool_capacity_loss": FleetSimulator.chaos_pool_capacity_loss,
    "store_outage": FleetSimulator.chaos_store_outage,
    "leader_partition": FleetSimulator.chaos_leader_partition,
    "agent_restart": FleetSimulator.chaos_agent_restart,
}

# Injection kinds with no sim adapter (the consistency test requires
# every INJECTION_KINDS entry to appear in exactly one of
# KIND_ADAPTERS / SIM_EXCLUDED_KINDS). The serving kinds target a
# serving fleet — HTTP replicas + a router, live token streams — not
# a batch pool; this simulator models scheduler/fleet dynamics, so
# they are drilled live instead (chaos/serving_drill.py,
# docs/37-serving-resilience.md).
SIM_EXCLUDED_KINDS: tuple = ("replica_kill", "replica_drain_notice",
                             "router_restart")

assert set(KIND_ADAPTERS) | set(SIM_EXCLUDED_KINDS) >= \
    set(INJECTION_KINDS)

# Mean service seconds of the steady/preemption-wave task shape
# (steps * step_seconds), used to size arrival rates to ~80% fleet
# utilization so queues neither explode nor stay empty.
_STEADY_STEPS = 100
_STEADY_STEP_SECONDS = 0.5


def _steady_rate(nodes: int, slots: int,
                 utilization: float = 0.65) -> float:
    service = _STEADY_STEPS * _STEADY_STEP_SECONDS
    return nodes * slots * utilization / service


def steady(seed: int, nodes: int, tasks: int) -> dict:
    """Steady Poisson arrivals at ~65% of bare-service utilization —
    sized so the queue stays SHORT even while compiles inflate
    effective service time (an overloaded queue ages every task past
    the affinity window and no placement policy can help it).

    One slot per node throughout (the TPU training shape): the
    goodput engine prices PER-NODE timelines, so one slot per node
    keeps one task's span from hiding behind a slot-mate's on the
    same timeline."""
    slots = 1
    return {
        "trace": traces.poisson_trace(
            seed, tasks, _steady_rate(nodes, slots),
            steps=_STEADY_STEPS,
            step_seconds=_STEADY_STEP_SECONDS,
            identities=max(4, nodes // 4), identity_fraction=0.8,
            compile_seconds=30.0, ckpt_every=20, ckpt_seconds=0.5),
        "nodes": nodes, "slots_per_node": slots}


def diurnal(seed: int, nodes: int, tasks: int) -> dict:
    """Sinusoidal day/night load with autoscale enabled: the
    provisioning-vs-queueing badput trade the goodput autoscale
    policy exists for."""
    slots = 1
    peak = _steady_rate(nodes, slots, utilization=1.1)
    return {
        "trace": traces.diurnal_trace(
            seed, tasks, day_seconds=3600.0, peak_rate=peak,
            trough_rate=0.15 * peak, steps=60,
            step_seconds=_STEADY_STEP_SECONDS,
            identities=max(4, nodes // 2), compile_seconds=30.0,
            ckpt_every=20),
        "nodes": max(1, nodes // 4), "slots_per_node": slots,
        "autoscale": True, "min_nodes": max(1, nodes // 8),
        "max_nodes": nodes, "provision_seconds": 120.0,
        # Knobs matched to the trace shape: the autoscale model's
        # backlog estimate uses avg_task_seconds, and this trace's
        # tasks run 60 steps x 0.5s.
        "knobs": PolicyKnobs(avg_task_seconds=30.0)}


def scheduler_scale(seed: int, nodes: int, tasks: int) -> dict:
    """BENCH_scheduler_scale-shaped: one streamed bulk submission of
    tiny identity-less tasks (10^6 by default at bench scale) — the
    queueing/claim-throughput regime, no compile or checkpoint legs.
    Deterministic regardless of seed."""
    del seed
    return {
        "trace": traces.scheduler_scale_trace(
            num_tasks=tasks, task_seconds=1.0),
        "nodes": nodes, "slots_per_node": 1}


def preemption_wave(seed: int, nodes: int, tasks: int) -> dict:
    """THE chaos-schedule scenario: steady load, then a provider
    preemption wave takes out 30% of the fleet mid-run — warm
    compile state destroyed, uncommitted steps replayed, a
    recovery-leg spike. Policies differ in how much of that badput
    they buy back."""
    base = steady(seed, nodes, tasks)
    plan = ChaosPlan.preemption_wave(
        seed, at=400.0, num_nodes=nodes,
        fraction=0.3, revive_after=60.0)
    return dict(base, injections=plan.injections)


def priority_burst(seed: int, nodes: int, tasks: int) -> dict:
    """Fleet saturated with low-priority fillers (half cadenced
    committers = cheap victims, half never-commit = expensive), then
    a high-priority burst that cannot place: the preemption sweep
    must elect victims, which is where goodput-cost victim selection
    shows up as avoided replay rework."""
    # The burst must be NARROWER than the fleet: a burst as wide as
    # the node count evicts every runner under any ordering and no
    # victim-selection policy can differ.
    burst = max(1, min(tasks // 10, nodes // 3))
    filler = max(1, tasks - burst)
    return {
        "trace": traces.priority_burst_trace(
            seed, filler_tasks=filler, burst_tasks=burst,
            burst_at=60.0, filler_steps=200,
            step_seconds=_STEADY_STEP_SECONDS, ckpt_every=50),
        "nodes": nodes, "slots_per_node": 1}


def chaos_soup(seed: int, nodes: int, tasks: int) -> dict:
    """Every batch-pool injection kind in one schedule (the full
    sim-expressible inventory as a scenario) — the smoke proof that
    every non-excluded chaos kind works in virtual time. The serving
    kinds (SIM_EXCLUDED_KINDS) are drilled live instead."""
    base = steady(seed, nodes, tasks)
    plan = ChaosPlan.generate(
        seed, duration=600.0, num_nodes=nodes,
        kinds=tuple(k for k in INJECTION_KINDS
                    if k not in SIM_EXCLUDED_KINDS),
        injections_per_kind=2)
    return dict(base, injections=plan.injections)


SCENARIOS: dict[str, Callable] = {
    "steady": steady,
    "diurnal": diurnal,
    "scheduler_scale": scheduler_scale,
    "preemption_wave": preemption_wave,
    "priority_burst": priority_burst,
    "chaos_soup": chaos_soup,
}

DESCRIPTIONS: dict[str, str] = {
    name: (fn.__doc__ or "").strip().split("\n")[0]
    for name, fn in SCENARIOS.items()
}


def build(name: str, seed: int, nodes: int, tasks: int) -> dict:
    if name not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; have "
            f"{', '.join(sorted(SCENARIOS))}")
    return SCENARIOS[name](seed, nodes, tasks)

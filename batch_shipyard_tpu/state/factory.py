"""State store factory: resolve credentials settings -> a StateStore."""

from __future__ import annotations

from batch_shipyard_tpu.config.settings import StorageCredentialsSettings
from batch_shipyard_tpu.state.base import StateStore

_SHARED_MEMORY_STORES: dict[str, StateStore] = {}


def create_statestore(storage: StorageCredentialsSettings) -> StateStore:
    if storage.backend == "memory":
        # Shared per-prefix within the process so CLI actions in one
        # process (and tests) observe each other's state.
        if storage.prefix not in _SHARED_MEMORY_STORES:
            from batch_shipyard_tpu.state.memory import MemoryStateStore
            _SHARED_MEMORY_STORES[storage.prefix] = MemoryStateStore()
        return _SHARED_MEMORY_STORES[storage.prefix]
    if storage.backend == "localfs":
        if not storage.root:
            raise ValueError("storage.root is required for localfs backend")
        from batch_shipyard_tpu.state.localfs import LocalFSStateStore
        return LocalFSStateStore(storage.root)
    if storage.backend == "gcs":
        if not storage.bucket:
            raise ValueError("storage.bucket is required for gcs backend")
        from batch_shipyard_tpu.state.gcs import GCSStateStore
        return GCSStateStore(storage.bucket, prefix=storage.prefix)
    raise ValueError(f"unknown storage backend {storage.backend!r}")

from batch_shipyard_tpu.substrate.base import (  # noqa: F401
    ComputeSubstrate,
    NodeInfo,
    create_substrate,
)

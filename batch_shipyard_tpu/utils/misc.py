"""Misc helpers: TensorBoard tunnel, image mirroring.

Reference analog: convoy/misc.py — tunnel_tensorboard(:62: pick the
logdir from a running task, start a TensorBoard container on its node,
local ssh port-forward) and image mirroring (:250).
"""

from __future__ import annotations

import os
from typing import Optional

from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import StateStore
from batch_shipyard_tpu.utils import crypto, util

logger = util.get_logger(__name__)

TENSORBOARD_PORT = 6006


def _resolve_task_login(store: StateStore, substrate, pool_id: str,
                        job_id: str, task_id: str
                        ) -> tuple[str, str, int]:
    """(node_id, ip, ssh_port) of the node a task is assigned to."""
    task = jobs_mgr.get_task(store, pool_id, job_id, task_id)
    node_id = task.get("node_id")
    if not node_id:
        raise ValueError(f"task {task_id} has no assigned node yet")
    login = substrate.get_remote_login(pool_id, node_id)
    if login is None:
        raise ValueError(f"no remote login for node {node_id}")
    ip, port = login
    return node_id, ip, port


def plan_tensorboard_tunnel(
        store: StateStore, substrate, pool_id: str, job_id: str,
        task_id: str, logdir: Optional[str] = None,
        local_port: int = 16006,
        ssh_username: str = "shipyard",
        ssh_private_key: Optional[str] = None,
        output_dir: str = ".") -> dict:
    """Resolve the task's node, synthesize the remote TensorBoard
    launch command and the local tunnel script (tunnel_tensorboard
    analog). Returns the plan; execution is the caller's choice."""
    node_id, ip, port = _resolve_task_login(store, substrate,
                                            pool_id, job_id, task_id)
    node = store.get_entity(names.TABLE_NODES, pool_id, node_id)
    if logdir is None:
        # Default: the task's working directory on the node.
        logdir = f"/var/shipyard/tasks/{job_id}/{task_id}"
    remote_cmd = (
        f"python3 -m tensorboard.main --logdir {logdir} "
        f"--port {TENSORBOARD_PORT} --bind_all")
    script_path = crypto.ssh_tunnel_script(
        ip, port, local_port, TENSORBOARD_PORT, ssh_username,
        ssh_private_key,
        os.path.join(output_dir, f"tunnel-tb-{task_id}.sh"))
    return {
        "node_id": node_id, "node_ip": ip, "ssh_port": port,
        "hostname": node.get("hostname"),
        "remote_command": remote_cmd,
        "tunnel_script": script_path,
        "local_url": f"http://localhost:{local_port}",
    }


def tunnel_tensorboard(store: StateStore, substrate, pool_id: str,
                       job_id: str, task_id: str,
                       logdir: Optional[str] = None,
                       local_port: int = 16006,
                       ssh_username: str = "shipyard",
                       ssh_private_key: Optional[str] = None,
                       output_dir: str = ".",
                       wait: bool = True) -> dict:
    """EXECUTE the TensorBoard tunnel (tunnel_tensorboard misc.py:62):
    start TensorBoard on the task's node over ssh, then run the local
    port-forward (blocking while the tunnel is up when wait=True).
    plan_tensorboard_tunnel remains the dry-run variant."""
    import subprocess

    plan = plan_tensorboard_tunnel(
        store, substrate, pool_id, job_id, task_id, logdir=logdir,
        local_port=local_port, ssh_username=ssh_username,
        ssh_private_key=ssh_private_key, output_dir=output_dir)
    rc, out, err = crypto.ssh_exec(
        plan["node_ip"],
        f"nohup {plan['remote_command']} >/tmp/tensorboard.log 2>&1 & "
        f"echo started",
        port=plan["ssh_port"], username=ssh_username,
        private_key_file=ssh_private_key)
    if rc != 0:
        raise RuntimeError(
            f"failed to start remote TensorBoard: {err.strip()}")
    logger.info("TensorBoard starting on %s; tunnel at %s",
                plan["node_id"], plan["local_url"])
    proc = subprocess.Popen(["bash", plan["tunnel_script"]])
    plan["tunnel_pid"] = proc.pid
    if wait:
        proc.wait()
    return plan


def plan_port_tunnel(store: StateStore, substrate, pool_id: str,
                     job_id: str, task_id: str, remote_port: int,
                     local_port: Optional[int] = None,
                     ssh_username: str = "shipyard",
                     ssh_private_key: Optional[str] = None,
                     output_dir: str = ".") -> dict:
    """Generic task-port tunnel (the TensorBoard-tunnel pattern for
    any service a task exposes — e.g. the serving front end's HTTP
    port from workloads/serve.py): resolve the task's node and write
    the local ssh port-forward script. Unlike the TensorBoard
    variant, nothing is launched remotely — the task is already
    listening."""
    node_id, ip, port = _resolve_task_login(store, substrate,
                                            pool_id, job_id, task_id)
    local_port = local_port or remote_port
    script_path = crypto.ssh_tunnel_script(
        ip, port, local_port, remote_port, ssh_username,
        ssh_private_key,
        os.path.join(output_dir,
                     f"tunnel-{task_id}-{remote_port}.sh"))
    return {
        "node_id": node_id, "node_ip": ip, "ssh_port": port,
        "remote_port": remote_port, "local_port": local_port,
        "tunnel_script": script_path,
        "local_url": f"http://localhost:{local_port}",
    }


def mirror_images_plan(images: list[str],
                       dest_registry: str) -> list[list[str]]:
    """Command plan to mirror images into a private registry
    (misc.py:250 analog)."""
    plan: list[list[str]] = []
    for image in images:
        target = f"{dest_registry}/{image.split('/')[-1]}"
        plan.append(["docker", "pull", image])
        plan.append(["docker", "tag", image, target])
        plan.append(["docker", "push", target])
    return plan


def mirror_images(images: list[str], dest_registry: str,
                  dry_run: bool = False) -> list[str]:
    """EXECUTE image mirroring into a private registry (misc.py:250):
    pull, tag, push each image; returns the mirrored targets. Raises
    on the first failing command."""
    import shutil
    import subprocess

    if not dry_run and shutil.which("docker") is None:
        raise RuntimeError("docker is required to mirror images")
    targets = []
    for argv in mirror_images_plan(images, dest_registry):
        if dry_run:
            logger.info("dry-run: %s", " ".join(argv))
        else:
            subprocess.run(argv, check=True)
        if argv[1] == "push":
            targets.append(argv[2])
    return targets

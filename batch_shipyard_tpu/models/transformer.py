"""Decoder-only transformer LM: the framework's flagship distributed
workload (the TensorFlow-Distributed/ResNet recipe analog for the
long-context era).

TPU-first design decisions:
  - bfloat16 activations/params with float32 RMSNorm statistics and
    attention accumulation (MXU-friendly, HBM-light);
  - attention is pluggable via config.attention_fn so the same module
    runs single-chip flash (Pallas), blockwise (XLA scan), or ring
    attention over the sp mesh axis (ops/ring_attention.py);
  - rotary position embeddings computed from *global* positions so
    sequence-parallel shards agree;
  - SwiGLU MLP sized to keep matmuls MXU-tiled (multiples of 128);
  - optional per-layer remat (jax.checkpoint) to trade FLOPs for HBM.

Tensor-parallel sharding is applied from outside via parameter
PartitionSpec rules (parallel/sharding.py) — the module itself stays
sharding-agnostic, which is what lets XLA insert the collectives.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from batch_shipyard_tpu.ops import attention as attn_ops
from batch_shipyard_tpu.ops import paged_attention as paged_ops


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_head: int = 64
    d_ff: int = 1408          # SwiGLU hidden (multiple of 128)
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    attention_fn: Optional[Callable] = None  # (q,k,v,causal)->out
    rope_theta: float = 10000.0
    # Mixture-of-experts: replace the MLP of every `moe_every`-th
    # block with routed experts (ep-shardable). None = dense.
    moe: Optional[Any] = None        # models.moe.MoEConfig
    moe_every: int = 2
    moe_aux_weight: float = 0.01
    # Autoregressive decode mode: attention maintains a KV cache (flax
    # 'cache' collection) and consumes one token step per call.
    decode: bool = False
    max_decode_len: int = 2048
    # Fuse each block's RMSNorm into its first projection matmul via
    # the Pallas kernel (ops/fused_norm.py): q/k/v collapse into one
    # [d, 3F] matmul and gate/up into one [d, 2*d_ff] matmul, with the
    # normalized activation never touching HBM. Changes the parameter
    # layout (qkv_kernel / gate_up_kernel instead of per-projection
    # Dense kernels) — opt-in, mutually exclusive with tp_axis /
    # quantize_matmuls / decode.
    fused_norm: bool = False
    # Run projection/MLP matmuls through the int8 Pallas kernels
    # (ops/quantization.py): both operands quantized per-row with
    # stochastic rounding, int32 MXU accumulation (2x the bf16 rate on
    # v5e), full-precision QAT backward. Opt-in — changes numerics.
    quantize_matmuls: bool = False
    # Quantize the decode KV cache (dense rows OR the paged pool) to
    # int8 with per-(position, head) scales: K/V absmax-quantize on
    # write and dequantize fused into the attention matmuls on read
    # (in-kernel per tile on the Pallas paged path) — half the HBM
    # per cached token vs bf16, so 2x the decode slots/context per
    # chip. Opt-in ("int8"); changes numerics within quantization
    # noise.
    kv_cache_dtype: Optional[str] = None
    # Paged KV cache for decode (vLLM-style): slots hold page-index
    # block tables into a shared page pool instead of reserving
    # max_decode_len rows each. None = dense cache.
    kv_page_size: Optional[int] = None
    kv_num_pages: int = 0
    # Speculative-decode write margin for the PAGED cache: widens each
    # slot's block table by ceil(spec_window/page) entries so a
    # draft/verify block starting near max_decode_len can spill its
    # (never-committed) tail writes past the logical length without
    # the table gather clamping onto a REAL page of the same slot.
    # The extra entries default to the allocator's scratch page, which
    # absorbs the garbage. Set by the serving engine to gamma; the
    # dense cache needs no margin (out-of-bounds scatters drop).
    spec_window: int = 0
    # Paged decode attention implementation: 'kernel' (Pallas, reads
    # only live pages via scalar-prefetched block tables), 'xla'
    # (gather over the full table width), or None = kernel on TPU and
    # xla elsewhere (ops/paged_attention.py dispatch).
    paged_attention_impl: Optional[str] = None
    # DENSE int8 decode attention implementation: 'kernel' (Pallas,
    # int8 cache + per-(position, head) scales dequantized in VMEM
    # per tile — HBM holds int8 + scales only), 'xla' (dequant
    # multiply outside the kernel, fused — or not — by XLA), or
    # None = auto, gated on the dense_decode_int8 silicon-validation
    # marker (ops/decode_attention.resolve_dense_decode_impl).
    decode_attention_impl: Optional[str] = None
    # Megatron-style tensor parallelism INSIDE a shard_map body (the
    # pipeline path): q/k/v/gate/up are column-sharded and
    # o_proj/down_proj row-sharded over this mesh axis, with explicit
    # psums after the row-sharded matmuls. The module then sees LOCAL
    # head/ff counts (configure n_heads/d_ff divided by tp). The
    # global-view jit path leaves this None — there XLA inserts the
    # collectives from parameter shardings.
    tp_axis: Optional[str] = None


def rotary_embedding(x, positions, theta: float):
    """Apply RoPE. x: [B, T, H, D]; positions: [T] global positions
    shared across the batch, or [B, T] per-sequence positions (the
    continuous-batching decode case, where each slot sits at its own
    depth)."""
    depth = x.shape[-1]
    freqs = jnp.exp(
        -jnp.log(theta) *
        jnp.arange(0, depth, 2, dtype=jnp.float32) / depth)
    angles = positions[..., None].astype(jnp.float32) * freqs
    if positions.ndim == 1:
        cos = jnp.cos(angles)[None, :, None, :]   # [1, T, 1, D/2]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        cos = jnp.cos(angles)[:, :, None, :]      # [B, T, 1, D/2]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_input(x, axis_name: str):
    """Megatron's "f" operator: identity forward, psum backward.

    Placed where a REPLICATED activation enters a tensor-parallel
    region (column-sharded matmuls): each tp member's backward
    produces only its shard's partial cotangent, and this is the
    point where those partials sum. Explicit custom_vjp — psum's AD
    transpose under shard_map is exactly the thing one should not
    lean on.
    """
    return x


def _tpi_fwd(x, axis_name):
    return x, None


def _tpi_bwd(axis_name, _res, g):
    return (jax.lax.psum(g, axis_name),)


tp_region_input.defvjp(_tpi_fwd, _tpi_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_region_output(x, axis_name: str):
    """Megatron's "g" operator: psum forward, identity backward.

    Placed where a tensor-parallel region's row-sharded partial sums
    leave it: forward reduces the partials; backward passes the
    (replicated) cotangent straight through to every member.
    """
    return jax.lax.psum(x, axis_name)


def _tpo_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _tpo_bwd(axis_name, _res, g):
    return (g,)


tp_region_output.defvjp(_tpo_fwd, _tpo_bwd)


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        norm = jnp.asarray(x, jnp.float32)
        norm = norm * jax.lax.rsqrt(
            jnp.mean(norm * norm, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        features = cfg.n_heads * cfg.d_head
        dense = functools_partial_dense(cfg)
        if cfg.fused_norm:
            # x arrives UN-normed; the block's attn RMSNorm is fused
            # into one [d, 3F] qkv projection (ops/fused_norm.py).
            from batch_shipyard_tpu.ops import fused_norm as fn_ops
            norm_scale = self.param(
                "norm_scale", nn.initializers.ones,
                (x.shape[-1],), jnp.float32)
            qkv_kernel = self.param(
                "qkv_kernel", nn.initializers.lecun_normal(),
                (x.shape[-1], 3 * features), cfg.param_dtype)
            batch, seq = x.shape[0], x.shape[1]
            qkv = fn_ops.rmsnorm_matmul(
                x.reshape(batch * seq, -1), norm_scale,
                qkv_kernel.astype(cfg.dtype))
            q, k, v = jnp.split(
                qkv.reshape(batch, seq, 3 * features), 3, axis=-1)
        else:
            if cfg.tp_axis:
                x = tp_region_input(x, cfg.tp_axis)
            q = dense(features, "q_proj")(x)
            k = dense(features, "k_proj")(x)
            v = dense(features, "v_proj")(x)
            batch, seq = x.shape[0], x.shape[1]
        q = q.reshape(batch, seq, cfg.n_heads, cfg.d_head)
        k = k.reshape(batch, seq, cfg.n_heads, cfg.d_head)
        v = v.reshape(batch, seq, cfg.n_heads, cfg.d_head)
        q = rotary_embedding(q, positions, cfg.rope_theta)
        k = rotary_embedding(k, positions, cfg.rope_theta)
        if cfg.decode:
            if cfg.tp_axis:
                raise NotImplementedError(
                    "tp_axis is a training-path (shard_map pipeline) "
                    "feature; the decode path would return "
                    "un-reduced o_proj partial sums")
            if cfg.kv_cache_dtype not in (None, "int8"):
                raise ValueError(
                    f"kv_cache_dtype={cfg.kv_cache_dtype!r}: only "
                    f"'int8' (or None) is supported")
            attend = (self._decode_attend_paged
                      if cfg.kv_page_size else self._decode_attend)
            return dense(cfg.d_model, "o_proj")(
                attend(q, k, v).reshape(batch, seq, features))
        attention_fn = cfg.attention_fn or (
            lambda q_, k_, v_, causal: attn_ops.attention(
                q_, k_, v_, causal=causal))
        out = attention_fn(q, k, v, causal=True)
        out = out.reshape(batch, seq, features)
        out = dense(cfg.d_model, "o_proj")(out)
        if cfg.tp_axis:
            # Row-sharded o_proj: each tp member holds a partial sum.
            out = tp_region_output(out, cfg.tp_axis)
        return out

    def _decode_attend(self, q, k, v):
        """Cache-writing decode attention. seq == 1 is the per-token
        decode step; seq > 1 is BATCHED PREFILL / chunked insert: all
        seq K/V rows land in the cache in one scatter and the queries
        attend causally over the cache in one MXU-batched pass —
        prefill wall-clock is one forward instead of L sequential
        micro-steps (VERDICT r2 order #2).

        The write index is PER SLOT ([B] int32), so independent
        sequences at different depths share one batched cache — the
        requirement for continuous batching (models/serving.py).
        Multi-token inserts start at each slot's current index."""
        cfg = self.config
        int8_kv = cfg.kv_cache_dtype == "int8"  # validated at dispatch
        store_dtype = jnp.int8 if int8_kv else cfg.dtype
        batch, seq, heads, depth = q.shape
        cache_k = self.variable(
            "cache", "k", jnp.zeros,
            (batch, cfg.max_decode_len, heads, depth), store_dtype)
        cache_v = self.variable(
            "cache", "v", jnp.zeros,
            (batch, cfg.max_decode_len, heads, depth), store_dtype)
        if int8_kv:
            # Per-(position, head) absmax scales; fp32 so dequant
            # error is the int8 rounding alone.
            scale_k = self.variable(
                "cache", "k_scale", jnp.zeros,
                (batch, cfg.max_decode_len, heads), jnp.float32)
            scale_v = self.variable(
                "cache", "v_scale", jnp.zeros,
                (batch, cfg.max_decode_len, heads), jnp.float32)

        if int8_kv:
            from batch_shipyard_tpu.ops.quantization import (
                quantize_int8_rows as quantize)

        index = self.variable(
            "cache", "index", lambda: jnp.zeros((batch,), jnp.int32))
        idx = index.value  # [B]
        key_pos = jax.lax.broadcasted_iota(
            jnp.int32, (cfg.max_decode_len, 1), 0)[:, 0]
        if seq == 1:
            rows = jnp.arange(batch)
            k_in, v_in = k[:, 0], v[:, 0]
            if int8_kv:
                k_in, ks = quantize(k_in)
                v_in, vs = quantize(v_in)
                scale_k.value = scale_k.value.at[rows, idx].set(ks)
                scale_v.value = scale_v.value.at[rows, idx].set(vs)
            cache_k.value = cache_k.value.at[rows, idx].set(
                k_in.astype(store_dtype))
            cache_v.value = cache_v.value.at[rows, idx].set(
                v_in.astype(store_dtype))
            index.value = idx + 1
            mask = (key_pos[None, :] <= idx[:, None])[:, None, None, :]
        else:
            rows = jnp.arange(batch)[:, None]                 # [B, 1]
            cols = idx[:, None] + jnp.arange(seq)[None, :]    # [B, S]
            k_in, v_in = k, v
            if int8_kv:
                k_in, ks = quantize(k_in)
                v_in, vs = quantize(v_in)
                scale_k.value = scale_k.value.at[rows, cols].set(ks)
                scale_v.value = scale_v.value.at[rows, cols].set(vs)
            cache_k.value = cache_k.value.at[rows, cols].set(
                k_in.astype(store_dtype))
            cache_v.value = cache_v.value.at[rows, cols].set(
                v_in.astype(store_dtype))
            index.value = idx + seq
            # Causal over absolute cache positions: query s (absolute
            # idx+s) sees keys <= idx+s — earlier chunks AND the
            # causal prefix of this one.
            mask = (key_pos[None, None, :] <=
                    cols[:, :, None])[:, None, :, :]  # [B, 1, S, T]
        if int8_kv and seq == 1:
            # Single-token decode dispatches through
            # ops/decode_attention: impl='kernel' dequantizes the
            # int8 rows + scales in VMEM tile by tile (no dequantized
            # cache ever exists in HBM — the dense_decode_hlo check
            # pins that on the compiled step); 'xla'/auto-fallback is
            # the dequant+einsum reference formulation. lengths =
            # keys visible to the query = idx + 1 (the key_pos <= idx
            # mask below, as a count).
            from batch_shipyard_tpu.ops import decode_attention as dd
            return dd.dense_decode_attention(
                q, cache_k.value, cache_v.value, scale_k.value,
                scale_v.value, idx + 1,
                impl=cfg.decode_attention_impl).astype(cfg.dtype)
        if int8_kv:
            # Multi-token prefill/insert path: dequant is elementwise
            # on the matmul operands — XLA fuses it into the dots (a
            # bet the int8_kv_dequant_fusion check measures); HBM
            # holds int8 + scales only
            # (ops/quantization.dequantize_int8 is the shared
            # contract partner of the quantize above).
            from batch_shipyard_tpu.ops import quantization as qz
            k_all = qz.dequantize_int8(
                cache_k.value,
                scale_k.value[..., None]).astype(cfg.dtype)
            v_all = qz.dequantize_int8(
                cache_v.value,
                scale_v.value[..., None]).astype(cfg.dtype)
        else:
            k_all, v_all = cache_k.value, cache_v.value
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_all,
            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(depth))
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v_all,
            preferred_element_type=jnp.float32)
        return out.astype(cfg.dtype)

    def _decode_attend_paged(self, q, k, v):
        """Paged decode attention (vLLM-style block tables): K/V live
        in a SHARED page pool [P, page, H, D]; each slot owns a row of
        page indices (block_table) covering only its actual length —
        the memory win over the dense cache is that the pool is sized
        for aggregate live tokens, not num_slots * max_decode_len.

        block_table/length are duplicated per layer (tiny int arrays)
        so everything stays inside the flax cache collection; the
        serving engine's page allocator mutates every layer's copy
        identically (models/serving.py).
        """
        cfg = self.config
        int8_kv = cfg.kv_cache_dtype == "int8"  # validated at dispatch
        store_dtype = jnp.int8 if int8_kv else cfg.dtype
        batch, seq, heads, depth = q.shape
        page = cfg.kv_page_size
        if seq > cfg.spec_window + 1:
            # Without table margin, a multi-token insert starting
            # within seq of max_decode_len would CLAMP its tail
            # gather onto the slot's last real page — silent cache
            # corruption. The serving engine sizes spec_window=gamma
            # for its gamma+1-token verify blocks; fail fast for any
            # other caller.
            raise ValueError(
                f"paged decode insert of {seq} tokens needs "
                f"spec_window >= {seq - 1} (got {cfg.spec_window}) "
                f"so tail writes spill onto scratch-backed table "
                f"entries instead of live pages")
        max_blocks = (cfg.max_decode_len + cfg.spec_window
                      + page - 1) // page
        k_pages = self.variable(
            "cache", "k_pages", jnp.zeros,
            (cfg.kv_num_pages, page, heads, depth), store_dtype)
        v_pages = self.variable(
            "cache", "v_pages", jnp.zeros,
            (cfg.kv_num_pages, page, heads, depth), store_dtype)
        if int8_kv:
            scale_k = self.variable(
                "cache", "k_page_scales", jnp.zeros,
                (cfg.kv_num_pages, page, heads), jnp.float32)
            scale_v = self.variable(
                "cache", "v_page_scales", jnp.zeros,
                (cfg.kv_num_pages, page, heads), jnp.float32)
        block_table = self.variable(
            "cache", "block_table",
            lambda: jnp.zeros((batch, max_blocks), jnp.int32))
        length = self.variable(
            "cache", "length", lambda: jnp.zeros((batch,), jnp.int32))
        idx = length.value                       # [B]
        # Absolute write positions per token, routed through the
        # slot's block table (seq > 1 is the speculative verify
        # block: y + gamma drafts insert at consecutive positions;
        # table entries past the slot's allocation point at the
        # engine's scratch page, which absorbs never-committed tail
        # writes — spec_window guarantees cols//page < max_blocks).
        cols = idx[:, None] + jnp.arange(seq)[None, :]        # [B, S]
        page_idx = jnp.take_along_axis(
            block_table.value, cols // page, axis=1)          # [B, S]
        offset = cols % page
        k_in, v_in = k, v
        if int8_kv:
            from batch_shipyard_tpu.ops.quantization import (
                quantize_int8_rows)
            k_in, ks = quantize_int8_rows(k_in)
            v_in, vs = quantize_int8_rows(v_in)
            scale_k.value = scale_k.value.at[page_idx, offset].set(ks)
            scale_v.value = scale_v.value.at[page_idx, offset].set(vs)
        k_pages.value = k_pages.value.at[page_idx, offset].set(
            k_in.astype(store_dtype))
        v_pages.value = v_pages.value.at[page_idx, offset].set(
            v_in.astype(store_dtype))
        length.value = idx + seq
        if seq == 1:
            return paged_ops.paged_decode_attention(
                q, k_pages.value, v_pages.value, block_table.value,
                length.value, impl=cfg.paged_attention_impl,
                k_scales=scale_k.value if int8_kv else None,
                v_scales=scale_v.value if int8_kv else None).astype(
                    cfg.dtype)
        # Multi-token verify pass: gather the slot's full logical view
        # and attend causally over absolute cache positions (query s
        # at position idx+s sees keys <= idx+s) — the paged analog of
        # the dense multi-token insert path above. Every key a
        # COMMITTED query can see is either prior committed state or
        # freshly written this block, so scratch-page garbage only
        # ever feeds draft positions whose logits get discarded.
        k_all = k_pages.value[block_table.value].reshape(
            batch, max_blocks * page, heads, depth)
        v_all = v_pages.value[block_table.value].reshape(
            batch, max_blocks * page, heads, depth)
        if int8_kv:
            ks_all = scale_k.value[block_table.value].reshape(
                batch, max_blocks * page, heads)
            vs_all = scale_v.value[block_table.value].reshape(
                batch, max_blocks * page, heads)
            k_all = (k_all.astype(jnp.float32) *
                     ks_all[..., None]).astype(cfg.dtype)
            v_all = (v_all.astype(jnp.float32) *
                     vs_all[..., None]).astype(cfg.dtype)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_all,
            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(depth))
        key_pos = jax.lax.broadcasted_iota(
            jnp.int32, (max_blocks * page, 1), 0)[:, 0]
        mask = (key_pos[None, None, :] <=
                cols[:, :, None])[:, None, :, :]      # [B, 1, S, T]
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", probs.astype(cfg.dtype), v_all,
            preferred_element_type=jnp.float32)
        return out.astype(cfg.dtype)



def prefix_rows_from_pages(layer_cache: dict, page_ids,
                           page: int) -> dict:
    """Gather a shared-prefix page chain out of ONE layer's paged
    pool into dense-cache row layout — the paged prefill entry point
    for cross-request prefix reuse (models/serving.py).

    The serving engine's prefix index stores immutable full pages by
    content hash; a request that matches n pages skips their prefill
    entirely and instead seeds a batch-1 dense cache with these rows
    (index = n*page), then runs only its suffix through the model.
    The gather works because a page id indexes EVERY layer's pool at
    the same position (the engine pushes one shared block table into
    all layers), so one id list reconstructs the prefix in each layer.

    layer_cache: one attention layer's paged leaves (k_pages
    [P, page, H, D], v_pages, and the int8 k_page_scales/v_page_scales
    [P, page, H] when present). page_ids: [n] int32 page indices —
    entries past the true prefix may point at the scratch page; their
    garbage rows are masked-on-read by the dense cache's index leaf.
    Returns {"k": [n*page, H, D], "v": ..., ("k_scale": [n*page, H],
    "v_scale": ...)} in the pool's storage dtype (int8 rows + fp32
    scales pass through untouched, so a shared prefix dequantizes to
    exactly the bytes the original prefill produced)."""
    k = layer_cache["k_pages"][page_ids]          # [n, page, H, D]
    rows = k.shape[0] * page
    out = {"k": k.reshape(rows, *k.shape[2:]),
           "v": layer_cache["v_pages"][page_ids].reshape(
               rows, *k.shape[2:])}
    if "k_page_scales" in layer_cache:
        ks = layer_cache["k_page_scales"][page_ids]
        out["k_scale"] = ks.reshape(rows, ks.shape[-1])
        out["v_scale"] = layer_cache["v_page_scales"][
            page_ids].reshape(rows, ks.shape[-1])
    return out


class QuantDense(nn.Module):
    """Bias-free linear layer running on the int8 MXU path.

    Parameter layout matches nn.Dense ("kernel" [in, features]) so the
    tensor-parallel PartitionSpec rules in parallel/sharding.py apply
    unchanged. Forward quantizes activations and weights per-row on
    the fly (ops/quantization.quantized_linear); backward is the
    standard full-precision QAT straight-through.
    """

    features: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        from batch_shipyard_tpu.ops import quantization as qz
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), self.param_dtype)
        flat = x.reshape(-1, x.shape[-1])
        out = qz.quantized_linear(flat, kernel.astype(self.dtype))
        return out.reshape(*x.shape[:-1],
                           self.features).astype(self.dtype)


def functools_partial_dense(cfg: TransformerConfig):
    def make(features: int, name: str):
        if getattr(cfg, "quantize_matmuls", False):
            return QuantDense(features, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype, name=name)
        return nn.Dense(features, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name=name)
    return make


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dense = functools_partial_dense(cfg)
        if cfg.fused_norm:
            # x arrives UN-normed; the block's mlp RMSNorm fuses into
            # one [d, 2*d_ff] gate/up projection.
            from batch_shipyard_tpu.ops import fused_norm as fn_ops
            norm_scale = self.param(
                "norm_scale", nn.initializers.ones,
                (x.shape[-1],), jnp.float32)
            gate_up_kernel = self.param(
                "gate_up_kernel", nn.initializers.lecun_normal(),
                (x.shape[-1], 2 * cfg.d_ff), cfg.param_dtype)
            batch, seq = x.shape[0], x.shape[1]
            gu = fn_ops.rmsnorm_matmul(
                x.reshape(batch * seq, -1), norm_scale,
                gate_up_kernel.astype(cfg.dtype))
            gate, up = jnp.split(
                gu.reshape(batch, seq, 2 * cfg.d_ff), 2, axis=-1)
            return dense(cfg.d_model, "down_proj")(
                nn.silu(gate) * up)
        if cfg.tp_axis:
            x = tp_region_input(x, cfg.tp_axis)
        gate = dense(cfg.d_ff, "gate_proj")(x)
        up = dense(cfg.d_ff, "up_proj")(x)
        out = dense(cfg.d_model, "down_proj")(nn.silu(gate) * up)
        if cfg.tp_axis:
            # Row-sharded down_proj: partial sums across tp members.
            out = tp_region_output(out, cfg.tp_axis)
        return out


class Block(nn.Module):
    config: TransformerConfig
    use_moe: bool = False

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.config
        if cfg.fused_norm:
            if (cfg.tp_axis or cfg.quantize_matmuls or cfg.decode
                    or self.use_moe):
                raise NotImplementedError(
                    "fused_norm composes only with the plain dense "
                    "training path (no tp_axis / quantize_matmuls / "
                    "decode / moe)")
            # The norms live INSIDE Attention/MLP (fused into their
            # first projection); pass the raw residual stream.
            x = x + Attention(cfg, name="attn")(x, positions)
            return x + MLP(cfg, name="mlp")(x)
        x = x + Attention(cfg, name="attn")(
            RMSNorm(dtype=cfg.dtype, name="attn_norm")(x), positions)
        normed = RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        if self.use_moe:
            from batch_shipyard_tpu.models.moe import MoEMLP
            out, aux = MoEMLP(cfg.moe, name="moe")(normed)
            self.sow("losses", "moe_aux", aux)
            x = x + out
        else:
            x = x + MLP(cfg, name="mlp")(normed)
        return x


class TransformerLM(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, tokens, return_hidden: bool = False,
                 positions=None):
        """tokens: [B, T] int32 -> logits [B, T, vocab] (or the final
        hidden states [B, T, d_model] when return_hidden — used by the
        chunked-loss training path so the full fp32 logits tensor,
        B*T*vocab, never materializes in HBM). In decode mode pass
        positions=[absolute position] for the current step."""
        cfg = self.config
        embed = nn.Embed(cfg.vocab_size, cfg.d_model,
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="embed")
        x = embed(tokens)
        if positions is None:
            positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for idx in range(cfg.n_layers):
            use_moe = (cfg.moe is not None and
                       idx % max(cfg.moe_every, 1) == (
                           max(cfg.moe_every, 1) - 1))
            x = block(cfg, use_moe, name=f"layer_{idx}")(x, positions)
        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        if return_hidden:
            return x
        # Tied output projection via attend (embedding transpose).
        logits = embed.attend(x.astype(jnp.float32))
        return logits


def lm_loss(logits, targets, ignore_id: int = -1):
    """Causal LM cross-entropy (next-token prediction is the caller's
    responsibility via target shifting)."""
    vocab = logits.shape[-1]
    mask = (targets != ignore_id)
    onehot = jax.nn.one_hot(targets, vocab, dtype=logits.dtype)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.sum(onehot * logprobs, axis=-1)
    return jnp.sum(loss * mask) / jnp.maximum(jnp.sum(mask), 1)


def lm_loss_chunked(hidden, embedding, targets, ignore_id: int = -1,
                    chunk_size: int = 128, impl: str = "auto"):
    """Memory-efficient tied-embedding cross-entropy.

    Never materializes the full [B, T, vocab] fp32 logits tensor (for
    T=2048, V=32k, B=16 that's 4 GB saved in the forward and again in
    the backward). Mathematically the same loss as
    lm_loss(embed.attend(hidden), targets), computed in fp32
    throughout (attend produces bf16 logits, so values differ at bf16
    precision — the chunked path is the more accurate one).

    Delegates to ops/chunked_loss.chunked_softmax_xent: impl='auto'
    runs the scan-chunked XLA path everywhere, upgrading to the fused
    Pallas kernel on a TPU backend once tools/tpu_checks.py has
    silicon-validated it (KERNEL_VALIDATION.json marker).
    """
    from batch_shipyard_tpu.ops import chunked_loss
    # chunk_size here means time-steps per batch row (the historical
    # contract); the flattened op counts rows, so scale by batch to
    # keep the per-slab matmul the same shape as before.
    rows = chunk_size * (hidden.shape[0] if hidden.ndim == 3 else 1)
    return chunked_loss.chunked_softmax_xent(
        hidden, embedding, targets, ignore_id=ignore_id, impl=impl,
        chunk_size=rows)

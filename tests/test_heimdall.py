"""Heimdall service discovery: file_sd target resolution from the
monitor table + the goodput Prometheus gauge export."""

import json
import os

from batch_shipyard_tpu.goodput import events as gp
from batch_shipyard_tpu.monitor import heimdall
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.memory import MemoryStateStore


def _store_with_pool_nodes():
    store = MemoryStateStore()
    store.upsert_entity(names.TABLE_POOLS, "pools", "pool1",
                        {"state": "ready"})
    for i, ip in enumerate(("10.0.0.1", "10.0.0.2")):
        store.upsert_entity(names.TABLE_NODES, "pool1", f"n{i}",
                            {"state": "idle", "internal_ip": ip})
    # A node with no ip yet (booting) must be skipped.
    store.upsert_entity(names.TABLE_NODES, "pool1", "nboot",
                        {"state": "creating"})
    return store


def test_file_sd_pool_targets(tmp_path):
    store = _store_with_pool_nodes()
    heimdall.add_pool_to_monitor(store, "pool1",
                                 node_exporter_port=9100,
                                 cadvisor_port=8080)
    path = heimdall.write_file_sd(store, str(tmp_path))
    assert os.path.basename(path) == "shipyard_targets.json"
    groups = json.load(open(path, encoding="utf-8"))
    by_job = {g["labels"]["job"]: g for g in groups}
    assert by_job["node_exporter"]["targets"] == [
        "10.0.0.1:9100", "10.0.0.2:9100"]
    assert by_job["node_exporter"]["labels"][
        "shipyard_pool"] == "pool1"
    assert by_job["cadvisor"]["targets"] == [
        "10.0.0.1:8080", "10.0.0.2:8080"]


def test_file_sd_remotefs_targets(tmp_path):
    store = MemoryStateStore()
    heimdall.add_remotefs_to_monitor(store, "nfs1",
                                     node_exporter_port=9100)
    store.upsert_entity(names.TABLE_REMOTEFS_NODES, "nfs1", "server0",
                        {"internal_ip": "10.1.0.9"})
    groups = heimdall.build_file_sd_targets(store)
    assert groups == [{
        "targets": ["10.1.0.9:9100"],
        "labels": {"job": "node_exporter",
                   "shipyard_remotefs": "nfs1"}}]


def test_deregistered_resource_disappears_on_next_poll(tmp_path):
    store = _store_with_pool_nodes()
    heimdall.add_pool_to_monitor(store, "pool1")
    path = heimdall.write_file_sd(store, str(tmp_path))
    assert json.load(open(path, encoding="utf-8"))
    heimdall.remove_resource_from_monitor(store, "pool$pool1")
    path = heimdall.write_file_sd(store, str(tmp_path))
    assert json.load(open(path, encoding="utf-8")) == []
    # Removing twice is a no-op, not an error.
    heimdall.remove_resource_from_monitor(store, "pool$pool1")


def test_goodput_prom_export(tmp_path):
    import time as time_mod
    store = _store_with_pool_nodes()
    # Recent epochs: the export only sweeps the trailing window.
    base = time_mod.time() - 200.0
    gp.emit(store, "pool1", gp.PROGRAM_STEP_WINDOW, job_id="j1",
            start=base, end=base + 75.0,
            attrs={"step_start": 0, "step_end": 75})
    gp.emit(store, "pool1", gp.PROGRAM_COMPILE, job_id="j1",
            start=base + 75.0, end=base + 100.0)
    # An ancient event outside the export window must not skew the
    # gauges.
    gp.emit(store, "pool1", gp.NODE_IDLE, node_id="n1",
            start=base - 10 * 24 * 3600, end=base - 10 * 24 * 3600
            + 5000)
    path = heimdall.write_goodput_metrics(store, str(tmp_path))
    assert os.path.basename(path) == "shipyard_goodput.prom"
    text = open(path, encoding="utf-8").read()
    assert 'goodput_ratio{pool="pool1"} 0.750000' in text
    assert 'badput_seconds{pool="pool1",category="compile"} 25.000' \
        in text
    # Every category is always present for dashboard stability.
    from batch_shipyard_tpu.goodput.accounting import (
        BADPUT_CATEGORIES)
    for category in BADPUT_CATEGORIES:
        assert f'category="{category}"' in text

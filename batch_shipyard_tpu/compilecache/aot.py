"""AOT precompile helpers: compile before the data shows up.

``jit`` compiles lazily on first call, so the first train step (or
the first real serving request of a new length bucket) pays the whole
XLA compile on the critical path. ``--aot-precompile`` flips that:
``jit(...).lower(abstract...).compile()`` runs against
``jax.ShapeDtypeStruct`` inputs — no data, no execution — so the
compile overlaps data-pipeline/loader startup (train) or happens
before the front end accepts traffic (serving), and with the
persistent cache enabled the result is durable across restarts.

Train harnesses (parallel/train.py) expose ``TrainHarness.precompile``
which swaps the AOT executable into the step hot path — the first
step then runs the SAME compiled program as the steady state, so
there is no cold-compile spike at all. ``precompile_async`` runs that
in a background thread under a goodput compile phase and returns a
join callable the workload invokes before its warm-up loop.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from batch_shipyard_tpu.compilecache import manager
from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def abstractify(tree: Any) -> Any:
    """Concrete array tree -> ShapeDtypeStruct tree (shardings kept),
    for lowering without touching data."""
    import jax

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=sharding)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def precompile_async(harness,
                     label: str = "train_step_aot"
                     ) -> Optional[Callable[[], None]]:
    """Start ``harness.precompile()`` on a background thread so the
    compile overlaps the caller's data/loader setup; the returned
    join callable blocks until it finishes. Failures degrade to the
    normal jit-on-first-step path (logged, never raised) — AOT is an
    optimization, not a correctness surface. Returns None when the
    harness has no precompile path."""
    precompile = getattr(harness, "precompile", None)
    if precompile is None:
        return None
    from batch_shipyard_tpu.goodput import events as goodput_events

    from batch_shipyard_tpu.trace import spans as trace_spans

    def _run() -> None:
        try:
            with goodput_events.phase(
                    goodput_events.PROGRAM_COMPILE,
                    what="aot_precompile") as attrs, \
                    manager.tracked(attrs, label), \
                    trace_spans.phase(trace_spans.SPAN_COMPILE,
                                      what="aot_precompile",
                                      label=label):
                precompile()
        except Exception:  # noqa: BLE001 - jit path still works
            logger.warning("AOT precompile failed; falling back to "
                           "jit-on-first-step", exc_info=True)

    thread = threading.Thread(target=_run, daemon=True,
                              name="aot-precompile")
    thread.start()
    return thread.join

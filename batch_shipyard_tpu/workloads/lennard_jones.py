"""Molecular-dynamics benchmark: the NAMD recipe analog
(/root/reference/recipes/NAMD-Infiniband-IntelMPI — parallel MD), as a
Lennard-Jones N-body velocity-Verlet integrator on the TPU.

All-pairs forces as one [N, N, 3] broadcast (the MXU/VPU-dense
formulation — for benchmark sizes the O(N^2) arithmetic beats
neighbor-list bookkeeping on this hardware); minimum-image periodic
boundaries; the time loop is one lax.scan. Reports particle-steps/sec
and verifies energy conservation (the MD correctness check).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from batch_shipyard_tpu.workloads import distributed


def lj_forces_energy(pos, box: float):
    """pos: [N, 3] -> (forces [N, 3], potential energy)."""
    disp = pos[:, None] - pos[None]                 # [N, N, 3]
    disp = disp - box * jnp.round(disp / box)       # minimum image
    r2 = jnp.sum(disp * disp, axis=-1)
    n = pos.shape[0]
    eye = jnp.eye(n, dtype=bool)
    r2 = jnp.where(eye, 1.0, r2)                    # mask self
    inv_r2 = 1.0 / r2
    inv_r6 = inv_r2 ** 3
    # F = 24 eps (2 r^-12 - r^-6) / r^2 * disp (eps = sigma = 1)
    fmag = 24.0 * (2.0 * inv_r6 * inv_r6 - inv_r6) * inv_r2
    fmag = jnp.where(eye, 0.0, fmag)
    forces = jnp.sum(fmag[..., None] * disp, axis=1)
    energy = 2.0 * jnp.sum(jnp.where(eye, 0.0,
                                     inv_r6 * inv_r6 - inv_r6))
    return forces, energy


def verlet_run(pos, vel, dt: float, box: float, steps: int):
    forces, _ = lj_forces_energy(pos, box)

    def step(carry, _):
        pos, vel, forces = carry
        vel_half = vel + 0.5 * dt * forces
        pos = (pos + dt * vel_half) % box
        forces_new, energy = lj_forces_energy(pos, box)
        vel = vel_half + 0.5 * dt * forces_new
        kinetic = 0.5 * jnp.sum(vel * vel)
        return (pos, vel, forces_new), energy + kinetic

    (pos, vel, _), total_energy = jax.lax.scan(
        step, (pos, vel, forces), None, length=steps)
    return pos, vel, total_energy


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--particles", type=int, default=4096)
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--dt", type=float, default=0.001)
    parser.add_argument("--density", type=float, default=0.5)
    args = parser.parse_args()
    ctx = distributed.setup()
    n = args.particles
    box = (n / args.density) ** (1.0 / 3.0)
    # Start from a jittered cubic lattice (avoids overlapping pairs).
    side = int(np.ceil(n ** (1 / 3)))
    grid = np.stack(np.meshgrid(*[np.arange(side)] * 3),
                    axis=-1).reshape(-1, 3)[:n]
    rng = np.random.RandomState(0)
    pos = jnp.asarray((grid + 0.5) * (box / side) +
                      0.05 * rng.randn(n, 3), jnp.float32)
    vel = jnp.asarray(rng.randn(n, 3) * 0.5, jnp.float32)
    vel = vel - jnp.mean(vel, axis=0, keepdims=True)
    run = jax.jit(lambda p, v: verlet_run(p, v, args.dt, box,
                                          args.steps))
    pos1, vel1, energy = run(pos, vel)
    pos1.block_until_ready()
    start = time.perf_counter()
    pos2, vel2, energy = run(pos1, vel1)
    pos2.block_until_ready()
    elapsed = time.perf_counter() - start
    psteps = n * args.steps / elapsed / 1e6
    e = np.asarray(energy)
    drift = abs(e[-1] - e[0]) / max(abs(e[0]), 1e-9)
    ok = np.all(np.isfinite(e)) and drift < 0.05
    distributed.log(ctx, (
        f"lennard_jones: N={n} {psteps:.2f} M particle-steps/s, "
        f"energy drift {drift * 100:.3f}% over {args.steps} steps "
        f"{'PASS' if ok else 'FAIL'}"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

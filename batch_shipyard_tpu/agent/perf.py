"""Perf event pipeline: phase-timestamp events into the perf table.

Reference analog: cascade/perf.py:55 process_event — timestamped rows
with microsecond collision bump, emitted at each nodeprep/cascade phase;
consumed offline by graph.py to produce per-node latency breakdowns.
This is the machinery behind the pool-add -> task-start latency metric
(BASELINE.md).
"""

from __future__ import annotations

import time
from typing import Optional

from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state.base import EntityExistsError, StateStore


def emit(store: StateStore, pool_id: str, node_id: str, source: str,
         event: str, message: Optional[str] = None,
         timestamp: Optional[float] = None) -> None:
    """Record one perf event; RowKey is the timestamp with a collision
    bump (reference perf.py RowKey scheme)."""
    ts = time.time() if timestamp is None else timestamp
    for bump in range(100):
        row_key = f"{ts + bump * 1e-6:017.6f}${node_id}${event}"
        try:
            # Collision-bump claim retry: ONE row, re-keyed until the
            # insert wins — not an n-item loop.
            store.insert_entity(names.TABLE_PERF, pool_id, row_key, {  # shipyard-lint: disable=store-write-in-loop
                "node_id": node_id, "source": source, "event": event,
                "message": message, "timestamp": ts,
            })
            return
        except EntityExistsError:
            continue


def query(store: StateStore, pool_id: str) -> list[dict]:
    return sorted(
        store.query_entities(names.TABLE_PERF, partition_key=pool_id),
        key=lambda e: e["timestamp"])

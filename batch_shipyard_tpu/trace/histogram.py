"""Fixed log-bucket latency histograms, mergeable across replicas.

The serving stack previously reported exact percentiles from
unbounded per-request lists — fine for one replica's own stats, but
percentiles of percentiles are meaningless, so the router (and any
fleet rollup) had nothing sound to aggregate. A histogram over a
FIXED geometric bucket ladder fixes that: every replica bins into the
same edges, so fleet-wide percentiles are computed after a lossless
counter merge, memory is O(buckets) regardless of traffic, and the
cumulative counts are exactly what Prometheus ``_bucket{le=...}``
export wants.

The ladder covers 0.25 ms .. ~35 min (0.25 * 2^23 ms) at 2x steps
(24 buckets + one overflow) — sub-bucket resolution is bounded at
2x, which is plenty
for p50/p90/p99 on serving latencies while keeping the wire/export
size trivial. Percentiles interpolate linearly inside the winning
bucket (lower edge for the overflow bucket), so p50 <= p90 <= p99
monotonicity holds by construction.
"""

from __future__ import annotations

from typing import Iterable, Optional

# Upper bucket edges in milliseconds: 0.25 * 2^k for k in [0, 24).
BUCKET_EDGES_MS: tuple[float, ...] = tuple(
    0.25 * (2.0 ** k) for k in range(24))


class LatencyHistogram:
    """Counts per fixed log bucket + exact sum/count/min/max."""

    __slots__ = ("counts", "overflow", "count", "total", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * len(BUCKET_EDGES_MS)
        self.overflow = 0
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value_ms: float) -> None:
        value_ms = max(0.0, float(value_ms))
        self.count += 1
        self.total += value_ms
        self.min = value_ms if self.min is None else min(self.min,
                                                         value_ms)
        self.max = value_ms if self.max is None else max(self.max,
                                                         value_ms)
        for k, edge in enumerate(BUCKET_EDGES_MS):
            if value_ms <= edge:
                self.counts[k] += 1
                return
        self.overflow += 1

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """In-place lossless merge (same fixed edges by construction);
        returns self for chaining."""
        self.counts = [a + b for a, b in zip(self.counts,
                                             other.counts)]
        self.overflow += other.overflow
        self.count += other.count
        self.total += other.total
        for bound, pick in (("min", min), ("max", max)):
            mine, theirs = getattr(self, bound), getattr(other, bound)
            if theirs is not None:
                setattr(self, bound,
                        theirs if mine is None else pick(mine, theirs))
        return self

    @classmethod
    def merged(cls, histograms: Iterable["LatencyHistogram"]
               ) -> "LatencyHistogram":
        out = cls()
        for histogram in histograms:
            out.merge(histogram)
        return out

    @classmethod
    def of(cls, values_ms: Iterable[float]) -> "LatencyHistogram":
        out = cls()
        for value in values_ms:
            out.observe(value)
        return out

    def percentile(self, pct: float) -> float:
        """Nearest-rank percentile with linear interpolation inside
        the winning bucket, clamped to the observed min/max so tiny
        samples don't report a bucket edge nobody hit. 0.0 when
        empty."""
        if not self.count:
            return 0.0
        import math
        rank = max(1, min(self.count,
                          math.ceil(pct / 100.0 * self.count)))
        seen = 0
        for k, edge in enumerate(BUCKET_EDGES_MS):
            if not self.counts[k]:
                continue
            if seen + self.counts[k] >= rank:
                lower = BUCKET_EDGES_MS[k - 1] if k else 0.0
                frac = (rank - seen) / self.counts[k]
                value = lower + (edge - lower) * frac
                break
            seen += self.counts[k]
        else:
            # Overflow bucket: its lower edge is the honest floor.
            value = BUCKET_EDGES_MS[-1]
        if self.min is not None:
            value = max(value, self.min)
        if self.max is not None:
            value = min(value, self.max)
        return value

    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentiles(self, pcts: tuple = (50, 90, 99)) -> dict:
        return {f"p{p}": self.percentile(p) for p in pcts}

    # ------------------------------ wire -------------------------------

    def to_dict(self) -> dict:
        """JSON-safe transport shape (server /v1/stats -> router
        merge)."""
        return {"edges_ms": list(BUCKET_EDGES_MS),
                "counts": list(self.counts),
                "overflow": self.overflow,
                "count": self.count, "total_ms": self.total,
                "min_ms": self.min, "max_ms": self.max}

    @classmethod
    def from_dict(cls, data: Optional[dict]
                  ) -> Optional["LatencyHistogram"]:
        """Parse the wire shape; None (not a crash) on junk or a
        foreign bucket ladder — a replica running older code must not
        poison the fleet merge."""
        if not isinstance(data, dict):
            return None
        counts = data.get("counts")
        edges = data.get("edges_ms")
        if not isinstance(counts, list) or \
                len(counts) != len(BUCKET_EDGES_MS) or \
                list(edges or ()) != list(BUCKET_EDGES_MS):
            return None
        out = cls()
        try:
            out.counts = [max(0, int(c)) for c in counts]
            out.overflow = max(0, int(data.get("overflow", 0)))
            out.count = max(0, int(data.get("count", 0)))
            out.total = max(0.0, float(data.get("total_ms", 0.0)))
            out.min = (None if data.get("min_ms") is None
                       else float(data["min_ms"]))
            out.max = (None if data.get("max_ms") is None
                       else float(data["max_ms"]))
        except (TypeError, ValueError):
            return None
        return out

    # --------------------------- prometheus ----------------------------

    def prometheus_bucket_lines(self, name: str,
                                labels: Optional[dict] = None
                                ) -> list[str]:
        """Cumulative ``{name}_bucket{{le=...}}`` lines plus
        ``{name}_sum`` / ``{name}_count`` — the native Prometheus
        histogram exposition, so ``histogram_quantile()`` works on
        the scrape."""
        base = dict(labels or {})
        inner = ",".join(f'{k}="{v}"' for k, v in sorted(base.items()))
        prefix = inner + "," if inner else ""
        lines = []
        cumulative = 0
        for edge, count in zip(BUCKET_EDGES_MS, self.counts):
            cumulative += count
            lines.append(f'{name}_bucket{{{prefix}le="{edge:g}"}} '
                         f"{cumulative}")
        lines.append(f'{name}_bucket{{{prefix}le="+Inf"}} '
                     f"{self.count}")
        suffix = "{" + inner + "}" if inner else ""
        lines.append(f"{name}_sum{suffix} {self.total:.6f}")
        lines.append(f"{name}_count{suffix} {self.count}")
        return lines

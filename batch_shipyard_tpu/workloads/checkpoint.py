"""Training checkpoint/resume via Orbax.

Reference context (SURVEY.md section 5.4): the reference has no
application checkpointing (it is an orchestrator); for the TPU build,
app-level checkpointing is a workload concern — this module gives the
recipe payloads a save/restore surface over Orbax so preempted or
migrated jobs resume instead of restarting. Orchestrator-level
suspend/resume and job migration live in pool/jobs managers.

Checkpoints go to a local path or, in a pool, typically the job's
shared directory (SHIPYARD_JOB_SHARED_DIR) or a gcsfuse mount so every
worker sees them.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from batch_shipyard_tpu.utils import util

logger = util.get_logger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def _step_path(checkpoint_dir: str, step: int) -> str:
    return os.path.join(os.path.abspath(checkpoint_dir),
                        f"step_{step:08d}")


def save(checkpoint_dir: str, step: int, params: Any,
         opt_state: Any) -> str:
    """Write checkpoint step N; returns its path."""
    import jax
    path = _step_path(checkpoint_dir, step)
    state = {"params": params, "opt_state": opt_state,
             "step": step}
    if jax.process_index() == 0:
        os.makedirs(checkpoint_dir, exist_ok=True)
    _checkpointer().save(path, state, force=True)
    logger.info("checkpoint saved: %s", path)
    return path


def latest_step(checkpoint_dir: str) -> Optional[int]:
    if not os.path.isdir(checkpoint_dir):
        return None
    steps = []
    for name in os.listdir(checkpoint_dir):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_", 1)[1]))
            except ValueError:
                continue
    return max(steps) if steps else None


def restore_params(checkpoint_dir: str) -> Optional[tuple]:
    """Restore only the params of the latest checkpoint (serving:
    the optimizer state is irrelevant and its template unavailable).
    Returns (params, step) or None. Arrays land unsharded on the
    default device — single-host serving replicas."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    restored = _checkpointer().restore(path)
    logger.info("checkpoint params restored: %s", path)
    return restored["params"], restored.get("step", step)


def restore(checkpoint_dir: str, params_template: Any,
            opt_state_template: Any) -> Optional[tuple]:
    """Restore the latest checkpoint matching the given pytree
    structure (shardings preserved from the templates); returns
    (params, opt_state, step) or None when no checkpoint exists."""
    step = latest_step(checkpoint_dir)
    if step is None:
        return None
    path = _step_path(checkpoint_dir, step)
    template = {"params": params_template,
                "opt_state": opt_state_template, "step": step}
    import orbax.checkpoint as ocp
    restored = _checkpointer().restore(
        path, item=template,
        restore_args=ocp.checkpoint_utils.construct_restore_args(
            template))
    logger.info("checkpoint restored: %s", path)
    return restored["params"], restored["opt_state"], restored["step"]

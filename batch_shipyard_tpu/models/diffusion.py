"""Diffusion transformer (DiT): denoising-diffusion image generation,
the framework's generative-vision model family.

The reference runs generative workloads only as opaque containers
(e.g. /root/reference/recipes/Chainer-GPU); here the model is part of
the TPU compute path. Architecture follows the public DiT recipe
(PAPERS.md): patchify -> N transformer blocks with adaLN-Zero timestep
conditioning -> linear head predicting per-patch noise.

TPU-first decisions:
  - patchify/unpatchify as reshapes + one Dense (MXU matmul, no conv);
  - adaLN modulation computed in fp32, activations bfloat16;
  - non-causal attention through ops/attention.attention (same Pallas
    flash / blockwise dispatch as the LM and ViT);
  - training loss draws (t, noise) with explicit jax PRNG keys — the
    whole step stays one jit with no host randomness;
  - DDIM sampler is a lax.fori_loop over static step count (no
    data-dependent control flow under jit).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from batch_shipyard_tpu.models.vit import LayerNorm, sincos_2d_positions
from batch_shipyard_tpu.ops import attention as attn_ops


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    image_size: int = 32
    channels: int = 3
    patch_size: int = 4
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    d_ff: int = 2048
    num_classes: Optional[int] = None   # class-conditional when set
    timesteps: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


def timestep_embedding(t, dim: int):
    """Sinusoidal timestep embedding [B] -> [B, dim] (fp32)."""
    half = dim // 2
    freqs = jnp.exp(-np.log(10000.0) *
                    jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _modulate(x, shift, scale):
    return x * (1.0 + scale[:, None]) + shift[:, None]


class DiTBlock(nn.Module):
    """Pre-LN transformer block with adaLN-Zero conditioning: the
    conditioning vector produces per-block shift/scale/gate for both
    the attention and MLP branches; gates initialize to zero so every
    block starts as identity (the DiT training stabilizer)."""
    config: DiTConfig

    @nn.compact
    def __call__(self, x, cond):
        cfg = self.config
        d_head = cfg.d_model // cfg.n_heads
        batch, seq = x.shape[0], x.shape[1]
        mod = nn.Dense(6 * cfg.d_model, dtype=jnp.float32,
                       param_dtype=cfg.param_dtype,
                       kernel_init=nn.initializers.zeros,
                       name="adaln")(nn.silu(cond))
        (shift_a, scale_a, gate_a, shift_m, scale_m,
         gate_m) = jnp.split(mod, 6, axis=-1)
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name)
        h = LayerNorm(dtype=jnp.float32, name="attn_norm")(x)
        h = _modulate(h, shift_a, scale_a).astype(cfg.dtype)
        q = dense(cfg.d_model, "q_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        k = dense(cfg.d_model, "k_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        v = dense(cfg.d_model, "v_proj")(h).reshape(
            batch, seq, cfg.n_heads, d_head)
        out = attn_ops.attention(q, k, v, causal=False)
        out = dense(cfg.d_model, "o_proj")(
            out.reshape(batch, seq, cfg.d_model))
        x = x + (gate_a[:, None] * out.astype(jnp.float32)).astype(
            x.dtype)
        h = LayerNorm(dtype=jnp.float32, name="mlp_norm")(x)
        h = _modulate(h, shift_m, scale_m).astype(cfg.dtype)
        h = dense(cfg.d_ff, "up_proj")(h)
        h = nn.gelu(h)
        h = dense(cfg.d_model, "down_proj")(h)
        return x + (gate_m[:, None] * h.astype(jnp.float32)).astype(
            x.dtype)


class DiT(nn.Module):
    config: DiTConfig

    @nn.compact
    def __call__(self, x_noisy, t, labels=None):
        """x_noisy: [B, H, W, C]; t: [B] int32; labels: [B] int32 when
        class-conditional. Returns predicted noise [B, H, W, C]."""
        cfg = self.config
        p = cfg.patch_size
        batch, height, width, chans = x_noisy.shape
        side = height // p
        patches = x_noisy.reshape(batch, side, p, side, p, chans)
        patches = patches.transpose(0, 1, 3, 2, 4, 5).reshape(
            batch, side * side, p * p * chans)
        x = nn.Dense(cfg.d_model, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype,
                     name="patch_embed")(patches.astype(cfg.dtype))
        pos = jnp.asarray(sincos_2d_positions(side, cfg.d_model),
                          cfg.dtype)
        x = x + pos[None]
        cond = timestep_embedding(t, cfg.d_model)
        cond = nn.Dense(cfg.d_model, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype,
                        name="t_embed_1")(cond)
        cond = nn.Dense(cfg.d_model, dtype=jnp.float32,
                        param_dtype=cfg.param_dtype,
                        name="t_embed_2")(nn.silu(cond))
        if cfg.num_classes is not None:
            if labels is None:
                raise ValueError("class-conditional DiT needs labels")
            cond = cond + nn.Embed(
                cfg.num_classes, cfg.d_model, dtype=jnp.float32,
                param_dtype=cfg.param_dtype, name="label_embed")(labels)
        for idx in range(cfg.n_layers):
            x = DiTBlock(cfg, name=f"block_{idx}")(x, cond)
        h = LayerNorm(dtype=jnp.float32, name="final_norm")(x)
        mod = nn.Dense(2 * cfg.d_model, dtype=jnp.float32,
                       param_dtype=cfg.param_dtype,
                       kernel_init=nn.initializers.zeros,
                       name="final_adaln")(nn.silu(cond))
        shift, scale = jnp.split(mod, 2, axis=-1)
        h = _modulate(h, shift, scale)
        out = nn.Dense(p * p * chans, dtype=jnp.float32,
                       param_dtype=cfg.param_dtype,
                       kernel_init=nn.initializers.zeros,
                       name="head")(h)
        out = out.reshape(batch, side, side, p, p, chans)
        out = out.transpose(0, 1, 3, 2, 4, 5).reshape(
            batch, height, width, chans)
        return out


def cosine_alpha_bar(timesteps: int) -> jnp.ndarray:
    """Cumulative noise schedule alpha_bar[t] (cosine, fp32)."""
    steps = jnp.arange(timesteps + 1, dtype=jnp.float32) / timesteps
    f = jnp.cos((steps + 0.008) / 1.008 * jnp.pi / 2) ** 2
    return jnp.clip(f[1:] / f[0], 1e-5, 1.0)


def diffusion_loss(model: DiT, params, x0, key, labels=None):
    """Epsilon-prediction MSE at uniformly sampled timesteps."""
    cfg = model.config
    t_key, n_key = jax.random.split(key)
    batch = x0.shape[0]
    t = jax.random.randint(t_key, (batch,), 0, cfg.timesteps)
    noise = jax.random.normal(n_key, x0.shape, jnp.float32)
    alpha_bar = cosine_alpha_bar(cfg.timesteps)[t]
    sqrt_ab = jnp.sqrt(alpha_bar)[:, None, None, None]
    sqrt_1mab = jnp.sqrt(1.0 - alpha_bar)[:, None, None, None]
    x_noisy = sqrt_ab * x0.astype(jnp.float32) + sqrt_1mab * noise
    pred = model.apply({"params": params},
                       x_noisy.astype(cfg.dtype), t, labels)
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - noise))


def ddim_sample(model: DiT, params, key, num_images: int,
                num_steps: int = 50, labels=None):
    """Deterministic DDIM sampler: num_steps uniform strides through
    the schedule, one lax.fori_loop (static shapes, jit-friendly)."""
    cfg = model.config
    shape = (num_images, cfg.image_size, cfg.image_size, cfg.channels)
    alpha_bar = cosine_alpha_bar(cfg.timesteps)
    ts = jnp.linspace(cfg.timesteps - 1, 0, num_steps).astype(jnp.int32)

    def body(i, x):
        t = ts[i]
        ab_t = alpha_bar[t]
        ab_prev = jnp.where(i + 1 < num_steps,
                            alpha_bar[ts[jnp.minimum(i + 1,
                                                     num_steps - 1)]],
                            1.0)
        t_vec = jnp.full((num_images,), t, jnp.int32)
        eps = model.apply({"params": params}, x.astype(cfg.dtype),
                          t_vec, labels).astype(jnp.float32)
        x0_hat = (x - jnp.sqrt(1.0 - ab_t) * eps) / jnp.sqrt(ab_t)
        x0_hat = jnp.clip(x0_hat, -1.0, 1.0)
        return jnp.sqrt(ab_prev) * x0_hat + \
            jnp.sqrt(1.0 - ab_prev) * eps

    x = jax.random.normal(key, shape, jnp.float32)
    return jax.lax.fori_loop(0, num_steps, body, x)

"""Streaming bulk submission, group-commit writes, batched claims.

The 10^6-task scheduler PR's proof obligations:

  * EQUIVALENCE: the streaming pipelined submitter produces task rows
    and queue messages BYTE-IDENTICAL to the legacy fixed-chunk
    submitter it replaced — trace columns, priority-band routing and
    multi-instance fan-out included. The optimization must be
    invisible to every consumer.
  * GROUP COMMIT: coalesced store writes never tear — a transport
    fault that lands mid-batch converges to exactly-once rows on
    retry, semantic errors surface without dropping neighbors, and a
    read inside the block sees every buffered write (flush-on-read).
  * SERVER-SIDE EXPANSION: a generator spec submitted as ONE row is
    materialized pool-side by the leader-gated expander and runs to
    completion with the goodput partition exact.
  * CLAIM BATCHING: a multi-slot agent takes k messages per poll.
  * The O(1) counting summary and the shard-count cache behave.
"""

from __future__ import annotations

import itertools
import json
import threading
import time

import pytest

from batch_shipyard_tpu.config import settings as settings_mod
from batch_shipyard_tpu.goodput import accounting
from batch_shipyard_tpu.jobs import expansion as expansion_mod
from batch_shipyard_tpu.jobs import manager as jobs_mgr
from batch_shipyard_tpu.pool import manager as pool_mgr
from batch_shipyard_tpu.state import names
from batch_shipyard_tpu.state import resilient as state_resilient
from batch_shipyard_tpu.state.base import (
    EntityExistsError, NotFoundError)
from batch_shipyard_tpu.state.memory import MemoryStateStore
from batch_shipyard_tpu.trace import context as trace_ctx
from batch_shipyard_tpu.utils import util

POOL_ID = "bulkpool"
JOB_ID = "bulk"


# ------------------------- equivalence property -------------------------

def _legacy_submit_tasks_batched(store, pool_id, job_id, tasks,
                                 priority=0, trace=None):
    """The pre-streaming submitter, verbatim (fixed 100-task chunks,
    one json.dumps per message): the reference implementation the
    equivalence property pins the new pipeline against."""
    chunk_size = 100
    pk = names.task_pk(pool_id, job_id)
    pool = store.get_entity(names.TABLE_POOLS, "pools", pool_id)
    shards = int(pool.get("spec", {}).get("pool_specification", {})
                 .get("task_queue_shards", 1))
    submitted_at = util.datetime_utcnow_iso()
    for chunk_start in range(0, len(tasks), chunk_size):
        chunk = tasks[chunk_start:chunk_start + chunk_size]
        rows = []
        for task_id, spec in chunk:
            entity = {
                "state": "pending", "spec": spec, "retries": 0,
                "submitted_at": submitted_at,
            }
            if trace is not None:
                entity.update(trace.child().entity_columns())
            rows.append((pk, task_id, entity))
        store.insert_entities(names.TABLE_TASKS, rows)
        by_queue = {}
        for task_id, spec in chunk:
            queue = names.task_queue_for(
                pool_id, task_id, shards,
                priority=int(spec.get("priority", priority) or 0))
            message = {"job_id": job_id, "task_id": task_id}
            if trace is not None:
                message["trace_id"] = trace.trace_id
            num_instances = (spec.get("multi_instance") or {}).get(
                "num_instances")
            if num_instances:
                by_queue.setdefault(queue, []).extend(
                    json.dumps({**message, "instance": k}).encode()
                    for k in range(num_instances))
            else:
                by_queue.setdefault(queue, []).append(
                    json.dumps(message).encode())
        for queue, payloads in by_queue.items():
            store.put_messages(queue, payloads)


def _make_store(shards):
    store = MemoryStateStore()
    store.insert_entity(names.TABLE_POOLS, "pools", POOL_ID, {
        "state": "ready",
        "spec": {"pool_specification": {
            "task_queue_shards": shards}}})
    return store


def _mixed_tasks(n):
    """A spec mix covering every encoding branch: generic + explicit
    ids, per-task priority overrides (both bands), and multi-instance
    gang fan-out."""
    tasks = []
    for i in range(n):
        spec = {"command": f"echo {i}"}
        if i % 7 == 3:
            spec["priority"] = -1
        elif i % 7 == 5:
            spec["priority"] = 1
        if i % 11 == 4:
            spec["multi_instance"] = {"num_instances": 3}
        tid = f"task-{i:05d}" if i % 5 else f"explicit.{i}"
        tasks.append((tid, spec))
    return tasks


def _drain_queue(store, queue):
    payloads = []
    while True:
        msgs = store.get_messages(queue, max_messages=32,
                                  visibility_timeout=600.0)
        if not msgs:
            return payloads
        payloads.extend(m.payload for m in msgs)


def _snapshot(store, shards):
    rows = {}
    for row in store.query_entities(
            names.TABLE_TASKS,
            partition_key=names.task_pk(POOL_ID, JOB_ID)):
        row = dict(row)
        row.pop("_etag", None)
        rows[row["_rk"]] = row
    queues = {q: _drain_queue(store, q)
              for q in names.task_queues(POOL_ID, shards)}
    return rows, queues


def _deterministic(monkeypatch):
    counter = itertools.count()
    monkeypatch.setattr(
        trace_ctx, "new_span_id",
        lambda: f"sp{next(counter):06x}")
    monkeypatch.setattr(
        util, "datetime_utcnow_iso",
        lambda: "2026-01-01T00:00:00.000000Z")
    return counter


@pytest.mark.parametrize("count", [37, 750])
def test_streaming_submitter_equivalent_to_legacy(monkeypatch, count):
    """Property: for a mixed workload (priorities, gangs, explicit
    ids) the streaming submitter's rows AND queue payloads are
    byte-identical to the legacy chunked submitter's — including the
    per-task trace columns and band/shard routing. 37 exercises the
    inline path, 750 the three-leg pipeline."""
    shards = 3
    tasks = _mixed_tasks(count)
    trace = trace_ctx.TraceContext(trace_id="0123456789abcdef",
                                   span_id="feedf00d")

    _deterministic(monkeypatch)
    legacy_store = _make_store(shards)
    _legacy_submit_tasks_batched(legacy_store, POOL_ID, JOB_ID,
                                 tasks, priority=0, trace=trace)
    legacy_rows, legacy_queues = _snapshot(legacy_store, shards)

    _deterministic(monkeypatch)  # reset the span counter
    new_store = _make_store(shards)
    stats = {}
    jobs_mgr._submit_tasks_batched(new_store, POOL_ID, JOB_ID, tasks,
                                   priority=0, trace=trace,
                                   stats=stats)
    new_rows, new_queues = _snapshot(new_store, shards)

    assert new_rows == legacy_rows
    assert new_queues == legacy_queues
    # And byte-identical, not merely ==, for the payloads:
    for queue in legacy_queues:
        assert [bytes(p) for p in new_queues[queue]] == \
            [bytes(p) for p in legacy_queues[queue]]
    assert stats["tasks"] == count
    assert stats["messages"] == sum(
        (spec.get("multi_instance") or {}).get("num_instances", 1)
        for _, spec in tasks)
    assert stats["chunks"] >= 1


def test_streaming_submitter_no_trace_no_priority(monkeypatch):
    """The untraced / default-priority corner emits identical bytes
    too (no trace columns, single band)."""
    _deterministic(monkeypatch)
    tasks = [(f"task-{i:05d}", {"command": "noop"})
             for i in range(150)]
    legacy_store = _make_store(2)
    _legacy_submit_tasks_batched(legacy_store, POOL_ID, JOB_ID, tasks)
    new_store = _make_store(2)
    jobs_mgr._submit_tasks_batched(new_store, POOL_ID, JOB_ID, tasks)
    assert _snapshot(new_store, 2) == _snapshot(legacy_store, 2)


def test_tolerant_resubmission_converges(monkeypatch):
    """tolerate_existing (the expander's resume path): re-submitting
    an already-landed chunk neither errors nor duplicates rows."""
    _deterministic(monkeypatch)
    store = _make_store(1)
    tasks = _mixed_tasks(30)
    jobs_mgr._submit_tasks_batched(store, POOL_ID, JOB_ID, tasks,
                                   tolerate_existing=True)
    jobs_mgr._submit_tasks_batched(store, POOL_ID, JOB_ID, tasks,
                                   tolerate_existing=True)
    rows = list(store.query_entities(
        names.TABLE_TASKS,
        partition_key=names.task_pk(POOL_ID, JOB_ID)))
    assert len(rows) == 30  # exactly once despite the re-apply


# ---------------------------- group commit ----------------------------

class _TornBatchStore(MemoryStateStore):
    """Applies the first ``tear_after`` rows of one insert_entities
    batch, then dies with a transport error — the partial-apply shape
    a real backend crash leaves behind."""

    def __init__(self, tear_after=3):
        super().__init__()
        self._tear_after = tear_after
        self._armed = 0
        self.insert_batches = 0

    def arm(self, times=1):
        self._armed = times

    def insert_entities(self, table, rows):
        self.insert_batches += 1
        if self._armed > 0:
            self._armed -= 1
            for pk, rk, entity in rows[:self._tear_after]:
                self.insert_entity(table, pk, rk, entity)
            raise ConnectionError("torn mid-batch")
        return super().insert_entities(table, rows)


def _resilient(inner, tmp_path, **kwargs):
    return state_resilient.ResilientStore(
        inner, journal_path=str(tmp_path / "wal.jsonl"),
        retry_base=0.01, retry_cap=0.05, **kwargs)


def test_group_commit_coalesces_and_flushes(tmp_path):
    """Adjacent batch writes coalesce into combined round trips; the
    block exit flushes everything; reads inside the block see the
    buffered writes first (flush-on-read)."""
    raw = MemoryStateStore()
    rs = _resilient(raw, tmp_path)
    pk = names.task_pk(POOL_ID, JOB_ID)
    with rs.group_commit():
        # Adjacent same-shape writes coalesce tail-wise; the kind
        # switch below starts a second buffered entry.
        for i in range(4):
            rs.insert_entities(names.TABLE_TASKS, [
                (pk, f"task-{4 * i + j:05d}",
                 {"state": "pending"}) for j in range(4)])
        for i in range(4):
            rs.put_messages("q-0", [b"m%d" % (4 * i + j)
                                    for j in range(4)])
        assert rs.group_commit_pending() > 0
        # Flush-on-read: a managed read op must observe the buffer.
        rows = list(rs.query_entities(names.TABLE_TASKS,
                                      partition_key=pk))
        assert len(rows) == 16
        assert rs.group_commit_pending() == 0
    assert rs.group_commits_total >= 1
    assert rs.group_commit_coalesced_total > 0
    assert len(list(raw.query_entities(
        names.TABLE_TASKS, partition_key=pk))) == 16
    assert raw.queue_length("q-0") == 16


def test_group_commit_never_tears_a_batch(tmp_path):
    """A transport fault that lands HALF an entity batch converges on
    retry: every row present exactly once, none lost, none doubled —
    the idempotent per-row repair discipline."""
    raw = _TornBatchStore(tear_after=5)
    rs = _resilient(raw, tmp_path)
    pk = names.task_pk(POOL_ID, JOB_ID)
    raw.arm(times=1)
    with rs.group_commit():
        rs.insert_entities(names.TABLE_TASKS, [
            (pk, f"task-{i:05d}", {"state": "pending", "n": i})
            for i in range(12)])
    rows = {r["_rk"]: r for r in raw.query_entities(
        names.TABLE_TASKS, partition_key=pk)}
    assert sorted(rows) == [f"task-{i:05d}" for i in range(12)]
    assert all(rows[f"task-{i:05d}"]["n"] == i for i in range(12))


def test_group_commit_defers_semantic_error_applies_rest(tmp_path):
    """A semantic error (EntityExistsError) inside a flushed batch is
    raised at the flush boundary — AFTER the remaining buffered
    entries applied. Semantic errors are successful round trips, not
    reasons to drop a neighbor's write."""
    raw = MemoryStateStore()
    pk = names.task_pk(POOL_ID, JOB_ID)
    raw.insert_entity(names.TABLE_TASKS, pk, "task-00001",
                      {"state": "pending"})
    rs = _resilient(raw, tmp_path)
    with pytest.raises(EntityExistsError):
        with rs.group_commit():
            rs.insert_entities(names.TABLE_TASKS, [
                (pk, "task-00001", {"state": "pending"})])
            rs.put_messages("q-0", [b"survivor"])
    assert raw.queue_length("q-0") == 1  # the neighbor still landed


def test_group_commit_under_chaos_store_faults(tmp_path):
    """ChaosStore-style transient errors during the flush retry
    through to exactly-once rows (critical-lane retry + per-row
    repair): the drill-facing guarantee."""
    from batch_shipyard_tpu.chaos import injectors as injectors_mod
    raw = MemoryStateStore()
    chaos = injectors_mod.ChaosStore(raw)
    rs = _resilient(chaos, tmp_path)
    pk = names.task_pk(POOL_ID, JOB_ID)
    chaos.inject_errors(2)
    with rs.group_commit():
        rs.insert_entities(names.TABLE_TASKS, [
            (pk, f"task-{i:05d}", {"state": "pending"})
            for i in range(8)])
        rs.put_messages("q-0", [b"x"] * 8)
    rows = list(raw.query_entities(names.TABLE_TASKS,
                                   partition_key=pk))
    assert len(rows) == 8
    # At-least-once on the queue leg: >= is the contract, duplicates
    # are claim-deduped downstream.
    assert raw.queue_length("q-0") >= 8


# ------------------------ server-side expansion ------------------------

def test_server_side_expansion_end_to_end():
    """One generator row in, N completed tasks out: the client leg is
    O(1), the pool's leader-gated expander materializes the job, the
    summary wait gates on expansion state, and the goodput partition
    stays exact with the expansion priced."""
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = MemoryStateStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.2,
                                 node_stale_seconds=30.0)
    substrate.agent_kwargs = {"claim_visibility_seconds": 30.0,
                              "gang_sweep_interval": 3600.0,
                              "preempt_sweep_interval": 3600.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 2}},
        "task_slots_per_node": 2,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({
            "job_specifications": [{
                "id": JOB_ID, "server_side_expansion": True,
                "tasks": [{"task_factory": {"repeat": 40},
                           "runtime": "inproc", "command": "noop"}],
            }]})
        submitted = jobs_mgr.add_jobs(store, pool, jobs)
        # O(1) client leg: no rows materialized client-side.
        assert submitted == {JOB_ID: 0}
        assert expansion_mod.expansion_state(store, POOL_ID,
                                             JOB_ID) in (
            "pending", "expanding", "completed")
        summary = jobs_mgr.wait_for_job_summary(
            store, POOL_ID, JOB_ID, timeout=60.0, poll_interval=0.2)
        assert summary["by_state"] == {"completed": 40}
        assert expansion_mod.expansion_state(
            store, POOL_ID, JOB_ID) == "completed"
        row = store.get_entity(names.TABLE_EXPANSIONS, POOL_ID,
                               JOB_ID)
        stats = row[names.EXPANSION_COL_STATS]
        assert stats["expanded"] == 40
        assert stats["tasks"] == 40
        report = accounting.pool_report(store, POOL_ID,
                                        include_jobs=False)
        total = (report["productive_seconds"]
                 + sum(report["badput_seconds"].values())
                 + sum(report["overlapped_seconds"].values()))
        assert abs(total - report["wall_seconds"]) <= max(
            1e-6 * max(1.0, report["wall_seconds"]), 1e-6)
        assert report["badput_seconds"]["expansion"] > 0
    finally:
        substrate.stop_all()


def test_expansion_bad_spec_fails_the_row():
    """An unparseable generator spec fails the expansion row (state
    "failed" + error) and the summary wait surfaces it instead of
    spinning forever."""
    store = MemoryStateStore()
    store.insert_entity(names.TABLE_POOLS, "pools", POOL_ID, {
        "state": "ready",
        "spec": {"pool_specification": {"id": POOL_ID,
                                        "substrate": "fake"}}})
    store.insert_entity(names.TABLE_JOBS, POOL_ID, JOB_ID,
                        {"state": "active"})
    store.insert_entity(names.TABLE_EXPANSIONS, POOL_ID, JOB_ID, {
        "state": "pending",
        "spec": {"id": JOB_ID,
                 "tasks": [{"task_factory": {"bogus": True}}]},
        names.EXPANSION_COL_CURSOR: 0})
    row = store.get_entity(names.TABLE_EXPANSIONS, POOL_ID, JOB_ID)
    assert not expansion_mod.run_expansion(store, POOL_ID, row)
    assert expansion_mod.expansion_state(store, POOL_ID,
                                         JOB_ID) == "failed"
    assert expansion_mod.expansion_error(store, POOL_ID, JOB_ID)
    with pytest.raises(RuntimeError):
        jobs_mgr.wait_for_job_summary(store, POOL_ID, JOB_ID,
                                      timeout=1.0)


def test_expansion_rejects_unseeded_random_factory():
    """An unseeded `random` factory would re-expand differently on
    leader handover — rejected at the client leg."""
    store = _make_store(1)
    bad = settings_mod._job_settings({
        "id": JOB_ID,
        "tasks": [{"task_factory": {
            "random": {"distribution": {"uniform": {"a": 0, "b": 1}},
                       "generate": 5}},
            "command": "noop {0}"}]})
    with pytest.raises(ValueError, match="deterministic"):
        expansion_mod.submit_expansion(store, POOL_ID, bad)
    store.insert_entity(names.TABLE_JOBS, POOL_ID, JOB_ID,
                        {"state": "active"})
    seeded = settings_mod._job_settings({
        "id": JOB_ID,
        "tasks": [{"task_factory": {
            "random": {"seed": 7,
                       "distribution": {"uniform": {"a": 0, "b": 1}},
                       "generate": 5}},
            "command": "noop {0}"}]})
    expansion_mod.submit_expansion(store, POOL_ID, seeded)
    assert expansion_mod.expansion_state(store, POOL_ID,
                                         JOB_ID) == "pending"


def test_expansion_yields_when_fenced_and_resumes():
    """A deposed expander yields with the cursor persisted; a
    successor re-runs the SAME deterministic factory, skips the
    cursor prefix, re-applies the boundary chunk idempotently, and
    completes with exactly N rows."""
    store = _make_store(1)
    job = settings_mod._job_settings({
        "id": JOB_ID,
        "tasks": [{"task_factory": {"repeat": 50},
                   "command": "noop"}]})
    store.insert_entity(names.TABLE_JOBS, POOL_ID, JOB_ID,
                        {"state": "active"})
    store.get_entity(names.TABLE_POOLS, "pools", POOL_ID)
    # Give the pool row a full spec so the expander can rebuild
    # PoolSettings.
    store.merge_entity(names.TABLE_POOLS, "pools", POOL_ID, {
        "spec": {"pool_specification": {
            "id": POOL_ID, "substrate": "fake",
            "task_queue_shards": 1}}})
    expansion_mod.submit_expansion(store, POOL_ID, job)
    row = store.get_entity(names.TABLE_EXPANSIONS, POOL_ID, JOB_ID)
    fence_calls = itertools.count()
    # Fence drops after two checks: the first chunk lands, then the
    # run yields mid-flight.
    done = expansion_mod.run_expansion(
        store, POOL_ID, row, chunk=20,
        fenced=lambda: next(fence_calls) < 2)
    assert not done
    resumed = store.get_entity(names.TABLE_EXPANSIONS, POOL_ID,
                               JOB_ID)
    assert resumed["state"] == "expanding"
    cursor = int(resumed[names.EXPANSION_COL_CURSOR])
    assert 0 < cursor < 50
    # Successor term: completes from the cursor.
    assert expansion_mod.run_expansion(store, POOL_ID, resumed,
                                       chunk=20)
    rows = list(store.query_entities(
        names.TABLE_TASKS,
        partition_key=names.task_pk(POOL_ID, JOB_ID)))
    assert len(rows) == 50  # exactly once, boundary chunk included
    assert expansion_mod.expansion_state(
        store, POOL_ID, JOB_ID) == "completed"


# --------------------------- batched claims ---------------------------

class _CountingStore(MemoryStateStore):
    """Counts get_messages calls and the largest batch a single task
    queue poll returned."""

    def __init__(self):
        super().__init__()
        self.taskq_polls = 0
        self.max_claimed = 0

    def get_messages(self, queue, max_messages=1,
                     visibility_timeout=30.0):
        msgs = super().get_messages(
            queue, max_messages=max_messages,
            visibility_timeout=visibility_timeout)
        if "taskq" in queue:
            self.taskq_polls += 1
            self.max_claimed = max(self.max_claimed, len(msgs))
        return msgs


def test_agent_claims_in_batches():
    """A 4-slot node claims up to slot-count messages per poll and
    still completes everything exactly once: fewer queue round trips
    than tasks, no lost or doubled work."""
    from batch_shipyard_tpu.substrate.fakepod import FakePodSubstrate
    store = _CountingStore()
    substrate = FakePodSubstrate(store, heartbeat_interval=0.5,
                                 node_stale_seconds=30.0)
    substrate.agent_kwargs = {"claim_visibility_seconds": 30.0,
                              "gang_sweep_interval": 3600.0,
                              "preempt_sweep_interval": 3600.0}
    conf = {"pool_specification": {
        "id": POOL_ID, "substrate": "fake",
        "vm_configuration": {"vm_count": {"dedicated": 1}},
        "task_slots_per_node": 4,
        "max_wait_time_seconds": 60}}
    pool = settings_mod.pool_settings(conf)
    try:
        pool_mgr.create_pool(store, substrate, pool,
                             settings_mod.global_settings({}), conf)
        jobs = settings_mod.job_settings_list({
            "job_specifications": [{
                "id": JOB_ID,
                "tasks": [{"task_factory": {"repeat": 32},
                           "runtime": "inproc", "command": "noop"}],
            }]})
        jobs_mgr.add_jobs(store, pool, jobs)
        summary = jobs_mgr.wait_for_job_summary(
            store, POOL_ID, JOB_ID, timeout=60.0, poll_interval=0.2)
        assert summary["by_state"] == {"completed": 32}
        assert store.max_claimed > 1  # batched claims actually used
    finally:
        substrate.stop_all()


# ------------------- counting summary + shards cache -------------------

def test_count_entities_by_memory_and_localfs(tmp_path):
    from batch_shipyard_tpu.state.localfs import LocalFSStateStore
    for store in (MemoryStateStore(),
                  LocalFSStateStore(str(tmp_path / "fs"))):
        pk = names.task_pk(POOL_ID, JOB_ID)
        states = (["completed"] * 5 + ["pending"] * 3
                  + ["running"] * 2)
        for i, state in enumerate(states):
            store.insert_entity(names.TABLE_TASKS, pk,
                                f"task-{i:05d}", {"state": state})
        store.insert_entity(names.TABLE_TASKS, pk, "task-weird",
                            {"note": "stateless"})
        store.insert_entity(names.TABLE_TASKS, "otherpk", "t0",
                            {"state": "pending"})
        counts = store.count_entities_by(names.TABLE_TASKS, pk)
        assert counts == {"completed": 5, "pending": 3,
                          "running": 2, "": 1}
        summary = jobs_mgr.job_task_summary(store, POOL_ID, JOB_ID)
        assert summary["total"] == 11
        assert summary["terminal"] == 5


def test_wait_for_job_summary_timeout_reports_states():
    store = _make_store(1)
    pk = names.task_pk(POOL_ID, JOB_ID)
    store.insert_entity(names.TABLE_TASKS, pk, "task-00000",
                        {"state": "pending"})
    with pytest.raises(TimeoutError) as err:
        jobs_mgr.wait_for_job_summary(store, POOL_ID, JOB_ID,
                                      timeout=0.3, poll_interval=0.1)
    assert "pending" in str(err.value)


def test_pool_queue_shards_cache_and_invalidation():
    store = _make_store(2)
    assert jobs_mgr.pool_queue_shards(store, POOL_ID) == 2
    pool = store.get_entity(names.TABLE_POOLS, "pools", POOL_ID)
    spec = dict(pool["spec"])
    spec["pool_specification"] = dict(spec["pool_specification"],
                                      task_queue_shards=4)
    store.merge_entity(names.TABLE_POOLS, "pools", POOL_ID,
                       {"spec": spec})
    # Cached value survives within the TTL...
    assert jobs_mgr.pool_queue_shards(store, POOL_ID) == 2
    # ...ttl=0 forces a fresh read without poisoning the cache path,
    # and explicit invalidation (the resize hook) drops it for good.
    assert jobs_mgr.pool_queue_shards(store, POOL_ID, ttl=0) == 4
    jobs_mgr.invalidate_pool_queue_shards(store, POOL_ID)
    assert jobs_mgr.pool_queue_shards(store, POOL_ID) == 4


def test_autoscale_queue_shards_grow_only():
    store = _make_store(2)
    # Below the per-shard rate: no change.
    assert jobs_mgr.maybe_autoscale_queue_shards(
        store, POOL_ID, tasks_per_second=100.0) == 2
    grown = jobs_mgr.maybe_autoscale_queue_shards(
        store, POOL_ID, tasks_per_second=20_000.0)
    assert grown == 8
    assert jobs_mgr.pool_queue_shards(store, POOL_ID) == 8
    # Grow-only: a later lower observation never shrinks.
    assert jobs_mgr.maybe_autoscale_queue_shards(
        store, POOL_ID, tasks_per_second=10.0) == 8
    # Old queue names are a strict subset of the new set, so
    # in-flight messages routed under 2 shards stay claimable.
    old = set(names.task_queues(POOL_ID, 2))
    new = set(names.task_queues(POOL_ID, 8))
    assert old < new
